"""The node agent (reference: client/client.go — Client :158,
registerAndHeartbeat :1484, watchAllocations :1924, runAllocs :2147,
batched allocSync :1858, restoreState :1032).

Register -> heartbeat on the server-granted TTL -> long-poll desired
allocations (a blocking query against the server's alloc index) -> diff
into alloc runners -> batch client-status updates back. On start the
agent restores runners from the state DB and re-attaches to live
workloads through each driver's RecoverTask.

The agent talks to servers through the narrow `ServerEndpoints`
interface; `InProcServer` adapts the in-process Server, and the RPC
transport drops in behind the same surface.
"""
from __future__ import annotations

import logging
import os
import threading
import time as _time
from typing import Dict, List, Optional, Tuple

from ..plugins.drivers import default_registry
from ..structs import NODE_STATUS_READY, Allocation, Node
from .allocrunner import AllocRunner
from .fingerprint import fingerprint_node
from .state import MemDB, StateDB

_log = logging.getLogger(__name__)

ALLOC_SYNC_INTERVAL_S = 0.2     # reference: client.go:93 allocSyncIntv
WATCH_TIMEOUT_S = 5.0
MAX_TERMINAL_RUNNERS = 50       # client-side GC bound (client/gc.go)


class ServerEndpoints:
    """The client<->server RPC surface (reference: Node.Register,
    Node.UpdateStatus, Node.GetClientAllocs, Node.UpdateAlloc)."""

    def register_node(self, node: Node) -> int:
        raise NotImplementedError

    def node_heartbeat(self, node_id: str) -> Optional[float]:
        raise NotImplementedError

    def get_client_allocs(self, node_id: str, min_index: int,
                          timeout: float) -> Tuple[List[Allocation], int]:
        raise NotImplementedError

    def update_allocs(self, updates: List[Allocation]) -> None:
        raise NotImplementedError

    def get_secret(self, namespace: str, path: str):
        """Fetch one secret's data dict (None if missing) — the task
        runner resolves ${secret...} references through this at task
        start (the Vault-token fetch analog)."""
        raise NotImplementedError

    def get_csi_volume(self, namespace: str, vol_id: str):
        """Resolve a registered CSI volume's details (None if missing)
        — consulted before staging (reference:
        client/pluginmanager/csimanager/volume.go)."""
        raise NotImplementedError

    def get_alloc_migrate_source(self, alloc_id: str):
        """For a replacement alloc's previous_allocation: the previous
        alloc's terminal-ness, owning node, advertised agent address,
        and a migrate token scoped to reading ITS alloc dir (reference:
        Node.GetClientAllocs returns MigrateTokens, client.go:925).
        None when the alloc is unknown (already GC'd)."""
        raise NotImplementedError


class InProcServer(ServerEndpoints):
    """Direct adapter over nomad_tpu.server.server.Server."""

    def __init__(self, server):
        self.server = server

    def register_node(self, node: Node) -> int:
        return self.server.register_node(node)

    def node_heartbeat(self, node_id: str) -> Optional[float]:
        return self.server.node_heartbeat(node_id)

    def get_client_allocs(self, node_id, min_index, timeout):
        return self.server.get_client_allocs(node_id, min_index, timeout)

    def update_allocs(self, updates: List[Allocation]) -> None:
        self.server.update_allocs_from_client(updates)

    def get_secret(self, namespace: str, path: str):
        return self.server.store.secret_by_path(namespace, path)

    def get_csi_volume(self, namespace: str, vol_id: str):
        return self.server.store.csi_volume_by_id(namespace, vol_id)

    def get_alloc_migrate_source(self, alloc_id: str):
        return self.server.alloc_migrate_source(alloc_id)


class Client:
    def __init__(self, servers: ServerEndpoints, data_dir: str,
                 node: Optional[Node] = None, registry=None,
                 datacenter: str = "dc1",
                 meta: Optional[Dict[str, str]] = None,
                 state_db=None, dev_mode: bool = False,
                 device_registry=None, tls=None):
        self.servers = (InProcServer(servers)
                        if not isinstance(servers, ServerEndpoints)
                        else servers)
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.registry = registry or default_registry()
        if device_registry is None:
            from ..plugins.device import default_device_registry
            device_registry = default_device_registry()
        self.device_registry = device_registry
        self.state_db = state_db if state_db is not None else (
            MemDB() if dev_mode
            else StateDB(os.path.join(data_dir, "client", "state.db")))
        from .csimanager import CSIManager
        self.csi_manager = CSIManager(data_dir)
        self.node = node or self._fingerprint_with_identity(datacenter, meta)
        if self.node.status != NODE_STATUS_READY:
            self.node.status = NODE_STATUS_READY
        self.runners: Dict[str, AllocRunner] = {}
        self._runners_lock = threading.Lock()
        self._updates: Dict[str, Allocation] = {}
        self._updates_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        #: utils.tlsutil.TLSConfig for dials to OTHER agents (disk
        #: migration streams); must match the cluster's HTTP plane
        self.tls = tls

    def _fingerprint_with_identity(self, datacenter, meta) -> Node:
        """Fingerprint the host, keeping a stable node identity across
        agent restarts (reference: the client persists NodeID/SecretID
        under <data_dir>/client)."""
        import json
        node = fingerprint_node(self.data_dir, self.registry,
                                datacenter=datacenter, meta=meta,
                                device_registry=self.device_registry)
        ident_path = os.path.join(self.data_dir, "client", "node.json")
        try:
            with open(ident_path) as f:
                ident = json.load(f)
            node.id = ident["id"]
            node.secret_id = ident["secret_id"]
        except (OSError, KeyError, ValueError):
            os.makedirs(os.path.dirname(ident_path), exist_ok=True)
            with open(ident_path, "w") as f:
                json.dump({"id": node.id, "secret_id": node.secret_id}, f)
        return node

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        self.restore_state()
        self.servers.register_node(self.node)
        for fn in (self._heartbeat_loop, self._watch_allocations,
                   self._alloc_sync_loop):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"client-{fn.__name__}")
            t.start()
            self._threads.append(t)

    def shutdown(self, halt_tasks: bool = False, leave: bool = False
                 ) -> None:
        """Stop the agent. With halt_tasks=False, workloads keep running
        under their executors — the restart/re-attach path
        (reference: agent restarts don't kill tasks)."""
        self._shutdown.set()
        if halt_tasks:
            with self._runners_lock:
                runners = list(self.runners.values())
            for r in runners:
                r.kill("agent shutting down")
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
        self.state_db.close()

    # ------------------------------------------------------------- restore
    def restore_state(self) -> None:
        """reference: client.go:1032 restoreState — rebuild runners from
        the state DB; each task runner re-attaches via RecoverTask."""
        for alloc in self.state_db.get_all_allocations():
            if alloc.server_terminal_status():
                continue
            try:
                runner = self._new_runner(alloc)
            except ValueError as e:
                _log.warning("restore %s: %s", alloc.id, e)
                continue
            runner.restore()
            with self._runners_lock:
                self.runners[alloc.id] = runner
            runner.run()

    # ------------------------------------------------------------- threads
    def _heartbeat_loop(self) -> None:
        """reference: client.go:1484 registerAndHeartbeat."""
        while not self._shutdown.is_set():
            try:
                ttl = self.servers.node_heartbeat(self.node.id)
            except Exception:
                _log.exception("heartbeat failed")
                ttl = None
            if ttl is None:
                # unknown node (server restarted / GC'd us): re-register
                try:
                    self.servers.register_node(self.node)
                except Exception:
                    _log.exception("re-register failed")
                ttl = 1.0
            self._shutdown.wait(max(ttl / 2.0, 0.05))

    def _watch_allocations(self) -> None:
        """reference: client.go:1924 watchAllocations — blocking query on
        the server's alloc-by-node index."""
        index = 0
        while not self._shutdown.is_set():
            try:
                allocs, index = self.servers.get_client_allocs(
                    self.node.id, index, WATCH_TIMEOUT_S)
            except Exception:
                _log.exception("watch_allocations failed")
                self._shutdown.wait(1.0)
                continue
            try:
                self._run_allocs(allocs)
            except Exception:
                _log.exception("run_allocs failed")

    def _run_allocs(self, desired: List[Allocation]) -> None:
        """Diff desired vs running (reference: client.go:2147 runAllocs)."""
        desired_by_id = {a.id: a for a in desired}
        with self._runners_lock:
            known = dict(self.runners)
        # removals: the server GC'd the alloc entirely
        for alloc_id, runner in known.items():
            if alloc_id not in desired_by_id:
                runner.destroy()
                with self._runners_lock:
                    self.runners.pop(alloc_id, None)
        for alloc in desired:
            runner = known.get(alloc.id)
            if runner is not None:
                if alloc.alloc_modify_index > \
                        runner.alloc.alloc_modify_index or \
                        alloc.modify_index > runner.alloc.modify_index:
                    runner.update(alloc)
                continue
            if alloc.server_terminal_status():
                continue               # never started here; nothing to do
            if alloc.client_terminal_status():
                continue               # finished in a previous life
            self.state_db.put_allocation(alloc)
            try:
                runner = self._new_runner(alloc)
            except ValueError as e:
                self._fail_alloc(alloc, str(e))
                continue
            with self._runners_lock:
                self.runners[alloc.id] = runner
            runner.run()
        self._gc_terminal_runners()

    def register_csi_plugin(self, name: str, addr) -> None:
        """Register an external CSI plugin endpoint and advertise it in
        the node fingerprint (reference: dynamic plugin registration +
        Node.CSINodePlugins)."""
        from ..structs import CSIPluginNodeInfo
        self.csi_manager.register_plugin(name, addr)
        self.node.csi_node_plugins[name] = CSIPluginNodeInfo(
            plugin_id=name, healthy=True)
        self.node.compute_class()
        # if already running, push the updated fingerprint
        if self._threads:
            self.servers.register_node(self.node)

    def _new_runner(self, alloc: Allocation) -> AllocRunner:
        return AllocRunner(alloc, self.data_dir, self.registry, self.node,
                           self._queue_update, state_db=self.state_db,
                           device_registry=self.device_registry,
                           secrets_fetcher=self.servers.get_secret,
                           csi_manager=self.csi_manager,
                           csi_resolver=self.servers.get_csi_volume,
                           prev_migrator=self.migrate_prev_alloc_dir)

    # ------------------------------------------- ephemeral-disk migration
    def migrate_prev_alloc_dir(self, alloc: Allocation,
                               dest_alloc_dir,
                               timeout_s: float = 60.0) -> None:
        """Bring a migrate=true previous alloc's shared data to this
        node before the replacement's tasks start (reference:
        client/allocwatcher/ — wait for the previous alloc to stop,
        then move its dir locally or stream it from the owning agent
        with a migrate token, client.go:925)."""
        import shutil
        import time as _t
        prev_id = alloc.previous_allocation
        deadline = _t.monotonic() + timeout_s
        src = None
        while True:
            try:
                src = self.servers.get_alloc_migrate_source(prev_id)
            except NotImplementedError:
                return                    # endpoint unsupported: skip
            if src is None:
                return                    # previous alloc already GC'd
            if src.get("terminal"):
                break
            if _t.monotonic() > deadline:
                raise RuntimeError(
                    f"timed out waiting for previous alloc "
                    f"{prev_id[:8]} to stop before disk migration")
            _t.sleep(0.2)
        dest_data = os.path.join(dest_alloc_dir.shared, "data")
        os.makedirs(dest_data, exist_ok=True)
        prev_runner = self.get_alloc_runner(prev_id)
        if prev_runner is not None:
            # local move (same node): reference allocwatcher's
            # local migration path
            src_data = os.path.join(prev_runner.alloc_dir.shared, "data")
            if os.path.isdir(src_data):
                shutil.copytree(src_data, dest_data, dirs_exist_ok=True)
            return
        addr = src.get("addr", "")
        if not addr:
            raise RuntimeError(
                "previous alloc's node has no advertised agent address "
                "to stream the ephemeral disk from")
        self._fetch_remote_alloc_data(addr, prev_id,
                                      src.get("migrate_token", ""),
                                      dest_data)

    def _fetch_remote_alloc_data(self, addr: str, prev_id: str,
                                 token: str, dest_data: str) -> None:
        """Recursively copy the previous alloc's alloc/data subtree
        through the owning agent's fs API."""
        from ..api.client import ApiClient
        scheme = ("https" if self.tls is not None
                  and self.tls.enabled() else "http")
        api = ApiClient(address=f"{scheme}://{addr}", token=token,
                        timeout=60.0, tls=self.tls)

        def walk(rel: str, dest: str) -> None:
            listing, _ = api.request(
                "GET", f"/v1/client/fs/ls/{prev_id}",
                params={"path": rel})
            for ent in listing.get("files", []):
                name = ent["name"]
                # the listing comes from a REMOTE agent: a compromised
                # or confused peer must not be able to steer the join
                # below outside dest ("../x", "a/b", "/etc/passwd")
                if (not name or name in (".", "..")
                        or "/" in name or "\\" in name
                        or os.path.isabs(name)):
                    raise RuntimeError(
                        f"remote fs listing returned unsafe entry name "
                        f"{name!r}")
                sub_rel = f"{rel}/{name}"
                sub_dest = os.path.join(dest, name)
                if ent["is_dir"]:
                    os.makedirs(sub_dest, exist_ok=True)
                    walk(sub_rel, sub_dest)
                    continue
                with open(sub_dest, "wb") as f:
                    off = 0
                    while True:
                        chunk, _ = api.request(
                            "GET", f"/v1/client/fs/readat/{prev_id}",
                            params={"path": sub_rel, "offset": off,
                                    "limit": 1 << 20})
                        data = __import__("base64").b64decode(
                            chunk.get("data", ""))
                        if not data:
                            break
                        f.write(data)
                        off += len(data)
                        # NOTE: a short (< limit) read is NOT EOF — the
                        # remote may return partial chunks under load;
                        # only an empty read ends the file, so a short
                        # read can never silently truncate a migration

        try:
            walk("alloc/data", dest_data)
        except Exception as e:
            raise RuntimeError(
                f"ephemeral disk migration from {addr} failed: {e}")

    def _fail_alloc(self, alloc: Allocation, reason: str) -> None:
        import copy
        from ..structs import ALLOC_CLIENT_FAILED
        upd = copy.copy(alloc)
        upd.client_status = ALLOC_CLIENT_FAILED
        upd.client_description = reason
        self._queue_update(upd)

    def _gc_terminal_runners(self) -> None:
        """Client-side GC (reference: client/gc.go AllocGarbageCollector,
        simplified to a count bound)."""
        with self._runners_lock:
            terminal = [(a_id, r) for a_id, r in self.runners.items()
                        if r.is_done()]
            excess = len(terminal) - MAX_TERMINAL_RUNNERS
            victims = terminal[:excess] if excess > 0 else []
            for a_id, _ in victims:
                self.runners.pop(a_id, None)
        for _, r in victims:
            r.destroy()

    # ---------------------------------------------------------- allocSync
    def _queue_update(self, alloc: Allocation) -> None:
        with self._updates_lock:
            self._updates[alloc.id] = alloc

    def _alloc_sync_loop(self) -> None:
        """Batched status push (reference: client.go:1858 allocSync)."""
        while not self._shutdown.is_set():
            self._shutdown.wait(ALLOC_SYNC_INTERVAL_S)
            self.flush_updates()

    def flush_updates(self) -> None:
        with self._updates_lock:
            if not self._updates:
                return
            batch = list(self._updates.values())
            self._updates.clear()
        try:
            self.servers.update_allocs(batch)
        except Exception:
            _log.exception("alloc sync failed; requeueing %d", len(batch))
            with self._updates_lock:
                for a in batch:
                    self._updates.setdefault(a.id, a)

    # ------------------------------------------------------------- queries
    def get_alloc_runner(self, alloc_id: str) -> Optional[AllocRunner]:
        with self._runners_lock:
            return self.runners.get(alloc_id)

    def num_allocs(self) -> int:
        with self._runners_lock:
            return len(self.runners)
