"""Per-task lifecycle runner (reference:
client/allocrunner/taskrunner/task_runner.go — Run :446 restart loop,
runDriver :717, handleKill :843, Restore + driver re-attach :971,:1019;
restart policy in client/allocrunner/taskrunner/restarts/).

One thread per task: prestart (task dir, env build) -> start driver ->
wait -> on exit consult the restart tracker -> restart or finalize.
Every transition persists {TaskHandle, TaskState} to the client state DB
so a restarted agent re-attaches instead of re-running.
"""
from __future__ import annotations

import copy
import logging
import os
import random
import threading
import time as _time
from typing import Callable, List, Optional

from ..plugins.drivers import (DriverError, DriverPlugin, ExitResult,
                               TaskConfig, TaskHandle, TaskNotFoundError)
from ..structs import (JOB_TYPE_BATCH, TASK_STATE_DEAD, TASK_STATE_PENDING,
                       TASK_STATE_RUNNING, Allocation, Node, Task, TaskEvent,
                       TaskState)
from .allocdir import AllocDir
from .taskenv import build_task_env, interpolate_config, node_vars

_log = logging.getLogger(__name__)

# task event types (reference: structs.TaskEvent consts)
EVENT_RECEIVED = "Received"
EVENT_SETUP = "Task Setup"
EVENT_STARTED = "Started"
EVENT_TERMINATED = "Terminated"
EVENT_RESTARTING = "Restarting"
EVENT_NOT_RESTARTING = "Not Restarting"
EVENT_KILLING = "Killing"
EVENT_KILLED = "Killed"
EVENT_DRIVER_FAILURE = "Driver Failure"
EVENT_TASK_LOST = "Task Lost"


class RestartTracker:
    """reference: client/allocrunner/taskrunner/restarts/restarts.go.

    Decides {restart, delay} after an exit: batch tasks restart only on
    failure; service/system tasks restart on any exit. Attempts are
    counted per policy interval; exceeding them either fails the task
    (mode=fail) or waits out the interval (mode=delay).
    """

    def __init__(self, policy, job_type: str):
        self.policy = policy
        self.job_type = job_type
        self.count = 0
        self.start = 0.0

    def next(self, result: Optional[ExitResult], killed: bool):
        """Returns (verdict, delay_s); verdict in
        {'restart', 'dead', 'failed'}."""
        if killed:
            return "dead", 0.0
        success = result is not None and result.successful()
        if self.job_type == JOB_TYPE_BATCH and success:
            return "dead", 0.0
        if self.policy is None or self.policy.attempts == 0:
            return ("dead" if success else "failed"), 0.0
        now = _time.time()
        if self.start == 0.0 or now - self.start > self.policy.interval_s:
            self.start = now
            self.count = 0
        self.count += 1
        delay = self.policy.delay_s * (1 + random.uniform(0, 0.25))
        if self.count <= self.policy.attempts:
            return "restart", delay
        if self.policy.mode == "delay":
            # wait out the rest of the interval, then the count resets
            remaining = self.policy.interval_s - (now - self.start)
            return "restart", max(remaining, 0.0) + delay
        return "failed", 0.0


class TaskRunner:
    def __init__(self, alloc: Allocation, task: Task, alloc_dir: AllocDir,
                 driver: DriverPlugin, node: Optional[Node],
                 on_state_change: Callable[["TaskRunner"], None],
                 state_db=None, device_registry=None,
                 secrets_fetcher=None):
        self.alloc = alloc
        self.task = task
        self.alloc_dir = alloc_dir
        self.driver = driver
        self.node = node
        self.on_state_change = on_state_change
        self.state_db = state_db
        self.device_registry = device_registry
        self.secrets_fetcher = secrets_fetcher
        self.task_id = f"{alloc.id}/{task.name}"
        self.state = TaskState(state=TASK_STATE_PENDING)
        self.handle: Optional[TaskHandle] = None
        self._kill = threading.Event()
        self._kill_reason = ""
        self._dead = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        self.restart_tracker = RestartTracker(
            tg.restart_policy if tg else None,
            job.type if job else "service")
        self._restored = False

    # ------------------------------------------------------------- state
    def task_state(self) -> TaskState:
        with self._lock:
            return copy.deepcopy(self.state)

    def _emit(self, etype: str, message: str = "", failed: bool = False,
              exit_code: int = 0) -> None:
        with self._lock:
            self.state.events.append(TaskEvent(
                type=etype, time=_time.time(), message=message,
                failure=failed, exit_code=exit_code))
            if len(self.state.events) > 10:
                del self.state.events[:len(self.state.events) - 10]

    def _set_state(self, state: str, failed: Optional[bool] = None) -> None:
        with self._lock:
            self.state.state = state
            if failed is not None:
                self.state.failed = failed
            if state == TASK_STATE_RUNNING and not self.state.started_at:
                self.state.started_at = _time.time()
            if state == TASK_STATE_DEAD:
                self.state.finished_at = _time.time()
        self._persist()
        self.on_state_change(self)

    # ------------------------------------------------------ service checks
    def _start_checks(self) -> None:
        """Run each service's checks on their intervals (reference: the
        consul agent runs registered checks; here the client runs them
        natively and the results ride task-state sync into the service
        catalog). Threads exit with the task; started at most once per
        runner (restarts reuse the running loops)."""
        with self._lock:
            if getattr(self, "_checks_started", False):
                return
            self._checks_started = True
        for svc in self.task.services:
            for check in svc.checks:
                t = threading.Thread(
                    target=self._check_loop, args=(svc, check),
                    daemon=True,
                    name=f"check-{self.task_id}-{check.name}")
                t.start()

    def _check_loop(self, svc, check) -> None:
        key = f"{svc.name}/{check.name or check.type}"
        # a check-level port_label overrides the service's (reference:
        # the check stanza's own port wins)
        port = self._service_port(check.port_label or svc.port_label)
        while not self._kill.is_set():
            ok = self._run_check(check, port)
            changed = False
            with self._lock:
                if self.state.checks.get(key) != ok:
                    self.state.checks[key] = ok
                    changed = True
            if changed:
                self._persist()
                self.on_state_change(self)
            if self._kill.wait(max(check.interval_s, 0.1)):
                return

    def _service_port(self, label: str):
        tr = self.alloc.allocated_resources.tasks.get(self.task.name)
        if tr is None or not label:
            return None
        for net in tr.networks:
            for p in (list(net.reserved_ports)
                      + list(net.dynamic_ports)):
                if p.label == label:
                    return p.value
        return None

    def _run_check(self, check, port) -> bool:
        import socket as _socket
        import subprocess as _subprocess
        try:
            if check.type == "tcp":
                if port is None:
                    return False
                with _socket.create_connection(
                        ("127.0.0.1", port),
                        timeout=max(check.timeout_s, 0.1)):
                    return True
            if check.type == "http":
                if port is None:
                    return False
                import urllib.request
                url = f"http://127.0.0.1:{port}{check.path or '/'}"
                with urllib.request.urlopen(
                        url, timeout=max(check.timeout_s, 0.1)) as r:
                    return 200 <= r.status < 400
            if check.type == "script":
                out = _subprocess.run(
                    [check.command] + list(check.args),
                    capture_output=True,
                    timeout=max(check.timeout_s, 0.1))
                return out.returncode == 0
        except Exception:               # noqa: BLE001
            return False
        return False                    # unknown check type: fail safe

    def _persist(self) -> None:
        if self.state_db is not None:
            with self._lock:
                handle = copy.deepcopy(self.handle)
                state = copy.deepcopy(self.state)
            self.state_db.put_task_runner_state(
                self.alloc.id, self.task.name, handle, state)

    # --------------------------------------------------------------- run
    def mark_failed(self, reason: str) -> None:
        """Fail the task without running it (alloc-level prerun hook
        failures — reference: alloc_runner.go prerun error path)."""
        self._emit(EVENT_DRIVER_FAILURE, message=reason, failed=True)
        self._set_state(TASK_STATE_DEAD, failed=True)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"taskrunner-{self.task_id}")
        self._thread.start()

    def run(self) -> None:
        try:
            self._run()
        except Exception as e:
            _log.exception("task runner %s crashed", self.task_id)
            self._emit(EVENT_DRIVER_FAILURE, message=str(e), failed=True)
            self._set_state(TASK_STATE_DEAD, failed=True)
        finally:
            self._dead.set()

    def _run(self) -> None:
        if self._restored and self.task_state().state == TASK_STATE_DEAD:
            return                     # restored an already-finished task
        self._emit(EVENT_RECEIVED)
        if not self._restored:
            self._prestart()
        while not self._kill.is_set():
            if self._restored and self.handle is not None:
                # re-attached to a live task: skip straight to wait
                # (checks must resume too — health would otherwise
                # freeze at the last persisted value)
                self._restored = False
                self._start_checks()
            else:
                self._restored = False
                try:
                    self._start_driver()
                except DriverError as e:
                    self._emit(EVENT_DRIVER_FAILURE, message=str(e),
                               failed=True)
                    verdict, delay = self.restart_tracker.next(
                        ExitResult(exit_code=-1, err=str(e)), killed=False)
                    if verdict == "restart" and not self._kill.wait(delay):
                        self._emit(EVENT_RESTARTING,
                                   message="driver failure")
                        continue
                    self._set_state(TASK_STATE_DEAD, failed=True)
                    return
            result = self._wait_driver()
            killed = self._kill.is_set()
            self._emit(EVENT_TERMINATED,
                       message=(result.err if result and result.err
                                else f"exit code {result.exit_code}"
                                if result else "killed"),
                       failed=bool(result and not result.successful()),
                       exit_code=result.exit_code if result else 0)
            self._destroy_driver_task()
            verdict, delay = self.restart_tracker.next(result, killed)
            if verdict == "restart":
                self._emit(EVENT_RESTARTING,
                           message=f"restarting in {delay:.1f}s")
                with self._lock:
                    self.state.restarts += 1
                    self.state.last_restart = _time.time()
                if self._kill.wait(delay):
                    break
                continue
            self._set_state(TASK_STATE_DEAD, failed=(verdict == "failed"))
            return
        # killed
        self._emit(EVENT_KILLED, message=self._kill_reason)
        self._set_state(TASK_STATE_DEAD, failed=False)

    # ----------------------------------------------------------- phases
    def _prestart(self) -> None:
        self._emit(EVENT_SETUP, message="Building Task Directory")
        self.alloc_dir.build()
        self.alloc_dir.build_task_dir(self.task.name)
        self._write_dispatch_payload()
        self._persist()
        self.on_state_change(self)

    def _write_dispatch_payload(self) -> None:
        """Deliver a dispatched job's payload into the task dir
        (reference: taskrunner/dispatch_hook.go — writes the payload to
        local/<dispatch_payload.file> before the task starts)."""
        dp = getattr(self.task, "dispatch_payload", None)
        job = self.alloc.job
        if not dp or not dp.file or job is None or not job.payload:
            return
        local = os.path.join(self.alloc_dir.task_dir(self.task.name), "local")
        dest = os.path.join(local, dp.file.lstrip("/"))
        # Containment check: the jobspec validates this at registration
        # (reference: structs/structs.go DispatchPayloadConfig.Validate →
        # PathEscapesAllocDir), but a payload path must never escape the
        # task dir even if a job bypassed validation (e.g. raw raft
        # restore), so re-check the normalized destination here too.
        localr = os.path.realpath(local)
        destr = os.path.realpath(os.path.dirname(dest))
        if destr != localr and not destr.startswith(localr + os.sep):
            raise RuntimeError(
                f"dispatch_payload file {dp.file!r} escapes the task's "
                "local directory")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        # The task itself can plant a symlink (agent writes outside the
        # sandbox) or a FIFO (open blocks forever) at the payload path
        # between runs: drop whatever is there and create fresh —
        # O_EXCL+O_NOFOLLOW closes the unlink→open race.
        dest = os.path.join(destr, os.path.basename(dest))
        try:
            os.unlink(dest)
        except FileNotFoundError:
            pass
        fd = os.open(dest, os.O_WRONLY | os.O_CREAT | os.O_EXCL
                     | os.O_NOFOLLOW, 0o644)
        with os.fdopen(fd, "wb") as f:
            f.write(job.payload)

    def _resolve_secrets(self, env: dict) -> dict:
        """Resolve ${secret.<path>.<key>} references in task env values
        against the server's native secret store (the Vault template
        analog: secrets reach the task as env, never touch server-side
        job state). An unresolvable reference fails the task at setup."""
        import re
        pat = re.compile(r"\$\{secret\.([A-Za-z0-9_\-/]+)\.([A-Za-z0-9_\-]+)\}")
        if self.secrets_fetcher is None:
            return env
        out = {}
        cache: dict = {}
        for k, v in env.items():
            def sub(m):
                path, key = m.group(1), m.group(2)
                if path not in cache:
                    try:
                        cache[path] = self.secrets_fetcher(
                            self.alloc.namespace, path)
                    except Exception as e:     # noqa: BLE001
                        # transport blip (leader election, network):
                        # recoverable — let the restart policy retry
                        # instead of permanently failing the task
                        raise DriverError(
                            f"secret fetch failed: {e}") from e
                data = cache[path]
                if data is None or key not in data:
                    raise RuntimeError(
                        f"unresolvable secret ${{secret.{path}.{key}}}")
                return data[key]
            out[k] = pat.sub(sub, v) if isinstance(v, str) else v
        return out

    def _device_envs(self) -> dict:
        """Reserve this task's assigned device instances through their
        owning plugins; their env recipe joins the task environment
        (reference: devicemanager Reserve at task start, devicehook)."""
        if self.device_registry is None:
            return {}
        tr = self.alloc.allocated_resources.tasks.get(self.task.name)
        if tr is None:
            return {}
        envs: dict = {}
        for ad in tr.devices:
            res = self.device_registry.reserve(
                ad.vendor, ad.type, ad.name, list(ad.device_ids))
            if res is None:
                # launching without the device recipe would hand the
                # task every host device (or crash it later) — fail at
                # setup like the reference devicehook does
                raise RuntimeError(
                    f"no device plugin owns {ad.vendor}/{ad.type}/"
                    f"{ad.name}; cannot reserve {ad.device_ids}")
            envs.update(res.envs)
        return envs

    def _task_config(self) -> TaskConfig:
        task_dir = self.alloc_dir.task_dir(self.task.name)
        env = build_task_env(
            self.alloc, self.task, self.node, task_dir=task_dir,
            alloc_dir=self.alloc_dir.shared,
            secrets_dir=self.alloc_dir.secrets_dir(self.task.name))
        env.update(self._device_envs())
        env = self._resolve_secrets(env)
        vars_ = dict(node_vars(self.node))
        vars_.update({f"env.{k}": v for k, v in env.items()})
        vars_.update(env)
        config = interpolate_config(self.task.config or {}, vars_)
        res = self.task.resources
        return TaskConfig(
            id=self.task_id, name=self.task.name, alloc_id=self.alloc.id,
            env=env, config=config, user=self.task.user,
            cpu_mhz=res.cpu if res else 0,
            memory_mb=res.memory_mb if res else 0,
            task_dir=task_dir, alloc_dir=self.alloc_dir.shared,
            stdout_path=self.alloc_dir.stdout_path(self.task.name),
            stderr_path=self.alloc_dir.stderr_path(self.task.name),
            log_max_files=(self.task.log_config.max_files
                           if self.task.log_config else 10),
            log_max_file_size_mb=(self.task.log_config.max_file_size_mb
                                  if self.task.log_config else 10))

    def _start_driver(self) -> None:
        handle = self.driver.start_task(self._task_config())
        with self._lock:
            self.handle = handle
        self._persist()
        self._emit(EVENT_STARTED)
        self._set_state(TASK_STATE_RUNNING)
        self._start_checks()

    def _wait_driver(self) -> Optional[ExitResult]:
        while not self._kill.is_set():
            result = self.driver.wait_task(self.task_id, timeout=0.2)
            if result is not None:
                return result
        # kill requested: stop through the driver, honoring kill_timeout
        try:
            self.driver.stop_task(self.task_id, self.task.kill_timeout_s,
                                  self.task.kill_signal)
        except TaskNotFoundError:
            return None
        except DriverError as e:
            _log.warning("stop_task %s: %s", self.task_id, e)
        return self.driver.wait_task(self.task_id, timeout=5.0)

    def _destroy_driver_task(self) -> None:
        try:
            self.driver.destroy_task(self.task_id, force=True)
        except (TaskNotFoundError, DriverError):
            pass
        with self._lock:
            self.handle = None
        self._persist()

    # ------------------------------------------------------------ verbs
    def kill(self, reason: str = "", wait: bool = True) -> None:
        self._emit(EVENT_KILLING, message=reason)
        self._kill_reason = reason
        self._kill.set()
        if wait and self._thread is not None:
            self._dead.wait(self.task.kill_timeout_s + 15.0)

    def is_dead(self) -> bool:
        return self._dead.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._dead.wait(timeout)

    # ---------------------------------------------------------- restore
    def restore(self) -> None:
        """Re-attach from the state DB (reference: task_runner.go:971
        Restore + :1019 restoreHandle). On a live handle the run loop
        resumes at wait; a lost task re-enters the restart loop."""
        if self.state_db is None:
            return
        handle, state = self.state_db.get_task_runner_state(
            self.alloc.id, self.task.name)
        if state is not None:
            with self._lock:
                self.state = state
        if state is not None and state.state == TASK_STATE_DEAD:
            # nothing to re-attach; mark runner finished
            self._restored = True
            self._dead.set()
            return
        if handle is None:
            return
        try:
            self.driver.recover_task(handle)
            status = self.driver.inspect_task(handle.task_id)
        except (TaskNotFoundError, DriverError) as e:
            self._emit(EVENT_TASK_LOST,
                       message=f"task not recoverable: {e}", failed=True)
            with self._lock:
                self.handle = None
            self._persist()
            return
        with self._lock:
            self.handle = handle
        self._restored = True
