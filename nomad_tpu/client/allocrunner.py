"""Per-allocation runner (reference: client/allocrunner/alloc_runner.go —
Run :276, Restore :380, task-state fan-in handleTaskStateUpdates :443
with leader-kill ordering, clientAlloc status rollup :600, destroy :803;
health watching from alloc_runner's health_hook + client/allochealth).

Owns one TaskRunner per task, rolls task states up into the alloc's
client status, watches deployment health, and reports every change
upward through `on_alloc_update` (the allocSync feed).
"""
from __future__ import annotations

import copy
import logging
import os
import threading
import time as _time
from typing import Callable, Dict, List, Optional

from ..structs import (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
                       ALLOC_CLIENT_PENDING, ALLOC_CLIENT_RUNNING,
                       TASK_STATE_DEAD, TASK_STATE_PENDING,
                       TASK_STATE_RUNNING, Allocation, AllocDeploymentStatus,
                       Node, TaskState)
from .allocdir import AllocDir
from .taskrunner import TaskRunner

_log = logging.getLogger(__name__)


def client_status_from_tasks(states: Dict[str, TaskState]) -> str:
    """reference: alloc_runner.go:600 clientAlloc / getClientStatus."""
    if not states:
        return ALLOC_CLIENT_PENDING
    vals = list(states.values())
    if any(ts.state == TASK_STATE_RUNNING for ts in vals):
        # a failed sibling makes the alloc failed even while others run
        if any(ts.failed for ts in vals):
            return ALLOC_CLIENT_FAILED
        return ALLOC_CLIENT_RUNNING
    if all(ts.state == TASK_STATE_DEAD for ts in vals):
        return (ALLOC_CLIENT_FAILED if any(ts.failed for ts in vals)
                else ALLOC_CLIENT_COMPLETE)
    if any(ts.failed for ts in vals):
        return ALLOC_CLIENT_FAILED
    return ALLOC_CLIENT_PENDING


class AllocRunner:
    def __init__(self, alloc: Allocation, data_dir: str, registry,
                 node: Optional[Node],
                 on_alloc_update: Callable[[Allocation], None],
                 state_db=None, device_registry=None,
                 secrets_fetcher=None, csi_manager=None,
                 csi_resolver=None, prev_migrator=None):
        self.alloc = alloc
        self.registry = registry
        self.device_registry = device_registry
        self.secrets_fetcher = secrets_fetcher
        self.csi_manager = csi_manager
        self.csi_resolver = csi_resolver
        #: callable(alloc, alloc_dir) bringing a migrate=true previous
        #: alloc's ephemeral disk here before tasks start (reference:
        #: client/allocwatcher prerun gate)
        self.prev_migrator = prev_migrator
        self._csi_mounts: List[tuple] = []   # (plugin, vol_id)
        self._vol_binds: List[str] = []      # task-dir bind mounts
        self.node = node
        self.on_alloc_update = on_alloc_update
        self.state_db = state_db
        self.alloc_dir = AllocDir(data_dir, alloc.id)
        self.task_runners: List[TaskRunner] = []
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._destroyed = False
        self._killing = False
        self._waiter: Optional[threading.Thread] = None
        self._health: Optional[threading.Thread] = None
        self._health_stop = threading.Event()
        self._health_reported: Optional[bool] = None
        self._build_runners()

    def _build_runners(self) -> None:
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        if tg is None:
            return
        for task in tg.tasks:
            driver = self.registry.get(task.driver)
            if driver is None:
                raise ValueError(f"unknown driver {task.driver!r} "
                                 f"for task {task.name}")
            self.task_runners.append(TaskRunner(
                self.alloc, task, self.alloc_dir, driver, self.node,
                self._on_task_state_change, state_db=self.state_db,
                device_registry=self.device_registry,
                secrets_fetcher=self.secrets_fetcher))

    # ---------------------------------------------------------- lifecycle
    def _mount_csi_volumes(self) -> None:
        """Prerun CSI hook (reference: alloc_runner_hooks.go csi_hook —
        stage/publish each task group CSI volume, then surface the
        published path inside every task dir at its volume_mounts
        destination).  A mount failure fails the whole alloc before any
        task starts, like the reference's prerun hook failure path."""
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        if tg is None or not getattr(tg, "volumes", None):
            return
        csi_reqs = {name: vr for name, vr in tg.volumes.items()
                    if vr.type == "csi"}
        if not csi_reqs:
            return
        if self.csi_manager is None or self.csi_resolver is None:
            raise RuntimeError("alloc requests CSI volumes but this "
                               "client has no CSI manager")
        targets: Dict[str, str] = {}
        for name, vr in csi_reqs.items():
            vol = self.csi_resolver(self.alloc.namespace, vr.source)
            if vol is None:
                raise RuntimeError(f"unknown CSI volume {vr.source!r}")
            target = self.csi_manager.mount(
                vol.plugin_id, vol.id, self.alloc.id,
                read_only=vr.read_only)
            self._csi_mounts.append((vol.plugin_id, vol.id))
            targets[name] = target
        for tr in self.task_runners:
            # destinations resolve under the task's working dir
            # (NOMAD_TASK_DIR = <task>/local — taskenv.py layout).
            # Bind mount when the host permits: a bind survives the
            # exec driver's chroot (the jail rbinds the task dir),
            # where a symlink to the client data dir would dangle.
            local = os.path.join(self.alloc_dir.task_dir(tr.task.name),
                                 "local")
            for vm in getattr(tr.task, "volume_mounts", []) or []:
                if vm.volume not in targets:
                    continue
                dest = os.path.join(local, vm.destination.lstrip("/"))
                if os.path.lexists(dest):
                    continue
                os.makedirs(dest, exist_ok=True)
                if self._try_bind(targets[vm.volume], dest,
                                  vm.read_only):
                    self._vol_binds.append(dest)
                else:
                    os.rmdir(dest)
                    os.symlink(targets[vm.volume], dest)

    @staticmethod
    def _try_bind(src: str, dst: str, read_only: bool) -> bool:
        try:
            from ..drivers.isolation import (MS_BIND, MS_RDONLY,
                                             MS_REMOUNT, _mount)
            _mount(src, dst, None, MS_BIND)
            if read_only:
                _mount(None, dst, None,
                       MS_REMOUNT | MS_BIND | MS_RDONLY)
            return True
        except OSError:
            return False

    def _unmount_csi_volumes(self) -> None:
        for dest in self._vol_binds:
            try:
                from ..plugins.csi import _try_unmount
                _try_unmount(dest)
                os.rmdir(dest)
            except OSError:
                pass
        self._vol_binds = []
        for plugin, vol_id in self._csi_mounts:
            try:
                self.csi_manager.unmount(plugin, vol_id, self.alloc.id)
            except Exception:
                pass
        self._csi_mounts = []

    def run(self) -> None:
        self.alloc_dir.build()
        try:
            self._migrate_prev_disk()
        except Exception as e:
            for tr in self.task_runners:
                tr.mark_failed(f"ephemeral disk migration failed: {e}")
            self._done.set()
            self._report()
            return
        try:
            self._mount_csi_volumes()
        except Exception as e:
            # release anything already staged/published before the
            # failing volume — otherwise stage refs and publish targets
            # leak until GC destroy
            self._unmount_csi_volumes()
            for tr in self.task_runners:
                tr.mark_failed(f"csi volume setup failed: {e}")
            self._done.set()
            self._report()
            return
        for tr in self.task_runners:
            if not tr.is_dead():
                tr.start()
        self._waiter = threading.Thread(target=self._wait_all, daemon=True)
        self._waiter.start()
        if self.alloc.deployment_id:
            self._health = threading.Thread(target=self._watch_health,
                                            daemon=True)
            self._health.start()
        # initial sync so the server sees pending promptly
        self._report()

    def _migrate_prev_disk(self) -> None:
        """Prerun gate: a replacement for a migrate=true group waits
        for its previous alloc to stop and pulls that alloc's shared
        data dir — locally or streamed from the owning agent
        (reference: client/allocwatcher/, migrate token client.go:925).
        Tasks must not start until the data is in place."""
        if self.prev_migrator is None:
            return
        if not self.alloc.previous_allocation:
            return
        if not self.alloc.migrate_disk():
            return
        self.prev_migrator(self.alloc, self.alloc_dir)

    def restore(self) -> None:
        """reference: alloc_runner.go:380 — restore every task runner
        from the state DB before run()."""
        for tr in self.task_runners:
            tr.restore()

    def _wait_all(self) -> None:
        for tr in self.task_runners:
            tr.wait()
        self._health_stop.set()
        # postrun: release the volume mounts once every task is done
        # (reference: csi_hook Postrun -> NodeUnpublish/NodeUnstage)
        self._unmount_csi_volumes()
        self._done.set()
        self._report()

    # -------------------------------------------------------- task fan-in
    def _on_task_state_change(self, tr: TaskRunner) -> None:
        # leader-task kill ordering (alloc_runner.go:443): when the leader
        # dies, the followers are killed
        if tr.task.leader and tr.task_state().state == TASK_STATE_DEAD:
            with self._lock:
                killing = self._killing
                self._killing = True
            if not killing:
                for other in self.task_runners:
                    if other is not tr and not other.is_dead():
                        threading.Thread(
                            target=other.kill,
                            args=("leader task dead",), daemon=True).start()
        self._report()

    def task_states(self) -> Dict[str, TaskState]:
        return {tr.task.name: tr.task_state() for tr in self.task_runners}

    def client_status(self) -> str:
        return client_status_from_tasks(self.task_states())

    def _report(self) -> None:
        upd = copy.copy(self.alloc)
        upd.task_states = self.task_states()
        upd.client_status = client_status_from_tasks(upd.task_states)
        upd.modify_time = _time.time()
        if self._health_reported is not None:
            upd.deployment_status = AllocDeploymentStatus(
                healthy=self._health_reported, timestamp=_time.time())
        self.on_alloc_update(upd)

    # ------------------------------------------------------------- health
    def _update_strategy(self):
        job = self.alloc.job
        tg = job.lookup_task_group(self.alloc.task_group) if job else None
        return tg.update if tg else None

    def _watch_health(self) -> None:
        """Deployment health tracker (reference: client/allochealth/
        tracker.go): healthy after min_healthy_time of everything running;
        unhealthy on any task failure or the healthy_deadline."""
        strategy = self._update_strategy()
        min_healthy = strategy.min_healthy_time_s if strategy else 10.0
        deadline = strategy.healthy_deadline_s if strategy else 300.0
        start = _time.time()
        healthy_since: Optional[float] = None
        seen_restarts = sum(ts.restarts
                            for ts in self.task_states().values())
        while not self._health_stop.wait(0.05):
            states = self.task_states()
            if any(ts.failed for ts in states.values()):
                self._set_health(False)
                return
            restarts = sum(ts.restarts for ts in states.values())
            if restarts > seen_restarts:
                seen_restarts = restarts
                healthy_since = None       # a restart resets the clock
            all_running = states and all(
                ts.state == TASK_STATE_RUNNING for ts in states.values())
            now = _time.time()
            if all_running:
                if healthy_since is None:
                    healthy_since = now
                if now - healthy_since >= min_healthy:
                    self._set_health(True)
                    return
            else:
                healthy_since = None
            if now - start > deadline:
                self._set_health(False)
                return

    def _set_health(self, healthy: bool) -> None:
        self._health_reported = healthy
        self._report()

    # -------------------------------------------------------------- verbs
    def update(self, alloc: Allocation) -> None:
        """Server pushed a new alloc version (reference: runAllocs update
        path). Stop/evict kills; otherwise adopt the new server-side
        fields (in-place update)."""
        with self._lock:
            self.alloc = alloc
            for tr in self.task_runners:
                tr.alloc = alloc
        if self.state_db is not None:
            self.state_db.put_allocation(alloc)
        if alloc.server_terminal_status():
            threading.Thread(target=self.kill,
                             args=("alloc stopped by server",),
                             daemon=True).start()

    def kill(self, reason: str = "") -> None:
        with self._lock:
            if self._killing:
                return
            self._killing = True
        for tr in self.task_runners:
            if not tr.is_dead():
                tr.kill(reason)
        self._done.wait(5.0)

    def destroy(self) -> None:
        """Full teardown incl. the alloc dir (client GC path)."""
        self.kill("alloc garbage collected")
        self._destroyed = True
        self._unmount_csi_volumes()
        self.alloc_dir.destroy()
        if self.state_db is not None:
            self.state_db.delete_allocation(self.alloc.id)

    def is_done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)
