"""PlanQueue: leader-side admission queue feeding the single plan applier.

Reference: nomad/plan_queue.go — priority heap of pending plans, each with
a future the submitting worker blocks on (:29, :58).
"""
from __future__ import annotations

import heapq
import itertools
import threading
from typing import List, Optional, Tuple

from ..structs import Plan, PlanResult


class PlanFuture:
    def __init__(self) -> None:
        self._event = threading.Event()
        self._result: Optional[PlanResult] = None
        self._err: Optional[str] = None

    def respond(self, result: Optional[PlanResult],
                err: Optional[str]) -> None:
        # first respond wins: the applier's error paths may race a
        # result already delivered (pipelined finalize), and a late
        # error must never overwrite what the worker already read
        if self._event.is_set():
            return
        self._result = result
        self._err = err
        self._event.set()

    def wait(self, timeout: float = 30.0
             ) -> Tuple[Optional[PlanResult], Optional[str]]:
        if not self._event.wait(timeout):
            return None, "plan apply timeout"
        return self._result, self._err


class PendingPlan:
    def __init__(self, plan: Plan):
        self.plan = plan
        self.future = PlanFuture()


class PlanQueue:
    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._enabled = False
        self._heap: List[tuple] = []
        self._count = itertools.count()

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                for _, _, pending in self._heap:
                    pending.future.respond(None, "plan queue disabled")
                self._heap.clear()
            self._lock.notify_all()

    @property
    def enabled(self) -> bool:
        with self._lock:    # guarded by _lock: see set_enabled
            return self._enabled

    def enqueue(self, plan: Plan) -> Optional[PendingPlan]:
        with self._lock:
            if not self._enabled:
                return None
            pending = PendingPlan(plan)
            heapq.heappush(self._heap,
                           (-plan.priority, next(self._count), pending))
            self._lock.notify_all()
            return pending

    def dequeue(self, timeout: float) -> Optional[PendingPlan]:
        import time
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                if self._heap:
                    return heapq.heappop(self._heap)[2]
                remain = deadline - time.monotonic()
                if remain <= 0 or not self._enabled:
                    return None
                self._lock.wait(remain)

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)
