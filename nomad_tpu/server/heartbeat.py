"""Leader-side node failure detector.

A single watcher thread over a deadline heap tracks every node's
heartbeat TTL (same pattern as the broker's delayed-eval watcher); expiry
marks the node down and (via the server's node-eval path) reschedules its
allocs. The TTL is rate-scaled to cluster size so aggregate heartbeat QPS
stays bounded (reference: nomad/heartbeat.go:34 nodeHeartbeater,
:90 resetHeartbeatTimer, :104 rate-scaled TTL via lib.RateScaledInterval,
:135 invalidateHeartbeat). The reference uses one time.Timer per node;
one Python thread per node would not scale to the 10K-node target, so
the deadline heap replaces the timer map — a reset simply moves the
node's authoritative deadline, and stale heap entries are skipped.
"""
from __future__ import annotations

import heapq
import logging
import random
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

_log = logging.getLogger(__name__)


def rate_scaled_interval(rate: float, min_s: float, n: int) -> float:
    """Interval targeting `rate` aggregate actions/sec across n actors
    (reference: consul lib.RateScaledInterval)."""
    if rate <= 0.0:
        return min_s
    interval = n / rate
    return max(interval, min_s)


class NodeHeartbeater:
    """Tracks heartbeat expiry per node (reference: nomad/heartbeat.go:34).

    `on_expire(node_id)` runs on the watcher thread when a node misses its
    TTL; the server wires it to update_node_status(down), which applies the
    status and fans out reschedule evals (SURVEY §3.3).
    """

    def __init__(self, on_expire: Callable[[str], None],
                 min_heartbeat_ttl_s: float = 10.0,
                 max_heartbeats_per_second: float = 50.0,
                 heartbeat_grace_s: float = 10.0,
                 failover_heartbeat_ttl_s: float = 300.0):
        self._on_expire = on_expire
        self.min_ttl = min_heartbeat_ttl_s
        self.max_rate = max_heartbeats_per_second
        self.grace = heartbeat_grace_s
        self.failover_ttl = failover_heartbeat_ttl_s
        # node id -> authoritative deadline; heap entries are advisory and
        # skipped unless they match the authoritative value
        self._deadlines: Dict[str, float] = {}
        self._heap: List[Tuple[float, str]] = []
        self._cv = threading.Condition()
        self._enabled = False
        self._watcher: Optional[threading.Thread] = None

    # ------------------------------------------------------------ control
    def set_enabled(self, enabled: bool) -> None:
        """Leadership gate: the watcher only runs on the leader
        (reference: heartbeat.go:94-100 IsLeader check)."""
        watcher = None
        with self._cv:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            if enabled:
                # thread handle guarded by _cv (nomadlint LOCK301)
                self._watcher = threading.Thread(target=self._watch,
                                                 daemon=True)
                self._watcher.start()
            else:
                self._deadlines.clear()
                self._heap.clear()
                watcher, self._watcher = self._watcher, None
                self._cv.notify_all()
        if watcher is not None:
            watcher.join(timeout=1.0)

    def initialize(self, node_ids) -> None:
        """On leadership gain, grant every known live node the failover TTL
        before expecting fresh heartbeats (reference: heartbeat.go:56
        initializeHeartbeatTimers)."""
        with self._cv:
            if not self._enabled:
                return
            now = _time.monotonic()
            for nid in node_ids:
                self._set_deadline_locked(nid, now + self.failover_ttl)
            self._cv.notify_all()

    # ---------------------------------------------------------- heartbeats
    def reset(self, node_id: str) -> Optional[float]:
        """Reset a node's TTL; returns the TTL the client should wait
        before its next heartbeat, or None if not leader
        (reference: heartbeat.go:90 resetHeartbeatTimer)."""
        with self._cv:
            if not self._enabled:
                return None
            n = len(self._deadlines)
            ttl = rate_scaled_interval(self.max_rate, self.min_ttl, n)
            ttl += random.uniform(0, ttl)   # stagger, reference :107
            self._set_deadline_locked(
                node_id, _time.monotonic() + ttl + self.grace)
            self._cv.notify_all()
            return ttl

    def _set_deadline_locked(self, node_id: str, deadline: float) -> None:
        self._deadlines[node_id] = deadline
        heapq.heappush(self._heap, (deadline, node_id))

    def clear(self, node_id: str) -> None:
        """Node became terminal: stop tracking it (the stale heap entry is
        skipped by the watcher; reference: heartbeat.go:171)."""
        with self._cv:
            self._deadlines.pop(node_id, None)

    def active(self) -> int:
        with self._cv:
            return len(self._deadlines)

    # ------------------------------------------------------------- watcher
    def _watch(self) -> None:
        while True:
            expired: List[str] = []
            with self._cv:
                if not self._enabled:
                    return
                now = _time.monotonic()
                while self._heap and self._heap[0][0] <= now:
                    deadline, nid = heapq.heappop(self._heap)
                    # only authoritative (not reset-superseded or cleared)
                    # entries expire the node
                    if self._deadlines.get(nid) == deadline:
                        del self._deadlines[nid]
                        expired.append(nid)
                if not expired:
                    wait = 0.5
                    if self._heap:
                        wait = min(wait, max(self._heap[0][0] - now, 0.001))
                    self._cv.wait(wait)
                    continue
            for nid in expired:
                # the callback races node deletion (reap_nodes); an exception
                # here must not kill the watcher and silently disable failure
                # detection for the whole cluster
                try:
                    self._on_expire(nid)
                except Exception:
                    _log.exception(
                        "heartbeat expiry callback failed for node %s", nid)
