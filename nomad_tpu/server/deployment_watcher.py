"""Leader-side deployment watcher (reference: nomad/deploymentwatcher/ —
Watcher deployments_watcher.go:60, per-deployment deployment_watcher.go,
health batching batcher.go).

Consumes the health counters the state store tracks as client updates
land, and reacts:
  - progress (new healthy allocs)  -> next-batch eval (rolling update)
  - all canaries healthy           -> auto-promote (or wait for manual)
  - any unhealthy alloc            -> fail; auto-revert to the latest
                                      stable job version if configured
  - progress deadline exceeded     -> fail (+ auto-revert)
  - all groups fully healthy       -> successful + mark job version stable

One watcher thread covers all deployments (the reference runs one
goroutine per deployment; the reaction logic is identical). Per-
deployment bookkeeping (last-seen counters, progress deadlines) is
leader-local in-memory state, as in the reference.
"""
from __future__ import annotations

import copy
import logging
import threading
import time as _time
from typing import Dict, Optional

from ..structs import (DEPLOYMENT_DESC_FAILED_ALLOCS,
                       DEPLOYMENT_DESC_PROGRESS_DEADLINE,
                       DEPLOYMENT_DESC_SUCCESSFUL,
                       DEPLOYMENT_STATUS_FAILED, DEPLOYMENT_STATUS_RUNNING,
                       DEPLOYMENT_STATUS_SUCCESSFUL,
                       EVAL_STATUS_PENDING, EVAL_TRIGGER_DEPLOYMENT_WATCHER,
                       EVAL_TRIGGER_ROLLING_UPDATE, Deployment,
                       DeploymentStatusUpdate, Evaluation)

_log = logging.getLogger(__name__)

DESC_AUTO_REVERT_SUFFIX = " - rolling back to job version {}"


class _DepState:
    __slots__ = ("healthy", "unhealthy", "placed", "promoted",
                 "progress_deadline")

    def __init__(self):
        self.healthy = -1
        self.unhealthy = 0
        self.placed = 0
        self.promoted = False
        self.progress_deadline = 0.0


class DeploymentWatcher:
    def __init__(self, server, poll_interval_s: float = 0.05):
        self.server = server
        self.poll_interval_s = poll_interval_s
        self._state: Dict[str, _DepState] = {}
        self._enabled = False
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ control
    def set_enabled(self, enabled: bool) -> None:
        thread = None
        with self._cv:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            if enabled:
                # thread handle guarded by _cv (nomadlint LOCK301)
                self._thread = threading.Thread(target=self._watch,
                                                daemon=True)
                self._thread.start()
            else:
                self._state.clear()
                thread, self._thread = self._thread, None
                self._cv.notify_all()
        if thread is not None:
            thread.join(timeout=1.0)

    # ------------------------------------------------------------- loop
    def _watch(self) -> None:
        store = self.server.store
        while True:
            with self._cv:
                if not self._enabled:
                    return
            try:
                for dep in list(store.deployments()):
                    if dep.active():
                        self._check(dep)
                    else:
                        with self._cv:
                            self._state.pop(dep.id, None)
            except Exception:
                _log.exception("deployment watcher pass failed")
            # block until new writes (health updates bump the store) or a
            # short tick for deadline checks
            store.wait_for_change(store.latest_index(),
                                  self.poll_interval_s * 4)

    # ------------------------------------------------------------ checks
    def _dep_state(self, dep_id: str) -> "_DepState":
        with self._cv:   # _state is cleared by set_enabled(False)
            st = self._state.get(dep_id)
            if st is None:
                st = self._state[dep_id] = _DepState()
            return st

    def _check(self, dep: Deployment) -> None:
        now = _time.time()
        st = self._dep_state(dep.id)
        healthy = sum(s.healthy_allocs for s in dep.task_groups.values())
        unhealthy = sum(s.unhealthy_allocs
                        for s in dep.task_groups.values())
        placed = sum(s.placed_allocs for s in dep.task_groups.values())

        # 1. failure: any alloc reported unhealthy
        if unhealthy > 0:
            self._fail(dep, DEPLOYMENT_DESC_FAILED_ALLOCS)
            return

        # 2. progress deadline (reference: deployment_watcher.go
        # watch's deadline timer; reset whenever progress is made)
        deadline_s = max((s.progress_deadline_s
                          for s in dep.task_groups.values()), default=0.0) \
            or self._job_progress_deadline(dep)
        if st.progress_deadline == 0.0 or healthy > max(st.healthy, 0):
            st.progress_deadline = now + deadline_s if deadline_s else 0.0
        if st.progress_deadline and now > st.progress_deadline:
            self._fail(dep, DEPLOYMENT_DESC_PROGRESS_DEADLINE)
            return

        # 3. canary auto-promotion
        if dep.requires_promotion():
            if dep.has_auto_promote() and self._canaries_healthy(dep):
                try:
                    self.server.promote_deployment(dep.id, all_groups=True)
                except ValueError:
                    pass               # canary health regressed; re-check
            st.healthy, st.unhealthy, st.placed = healthy, unhealthy, placed
            return

        # 4. complete: every group fully healthy
        complete = all(s.healthy_allocs >= s.desired_total
                       for s in dep.task_groups.values())
        if complete and dep.status == DEPLOYMENT_STATUS_RUNNING:
            self._succeed(dep)
            return

        # 5. progress: new healthy allocs unblock the next rolling batch.
        # The baseline is 0, not the first observation — health reported
        # before our first scan still counts as progress, otherwise the
        # rollout stalls until the progress deadline kills it
        if healthy > max(st.healthy, 0):
            self._create_eval(dep, EVAL_TRIGGER_DEPLOYMENT_WATCHER)
        st.healthy, st.unhealthy, st.placed = healthy, unhealthy, placed

    def _job_progress_deadline(self, dep: Deployment) -> float:
        job = self.server.store.job_by_id(dep.namespace, dep.job_id)
        if job is None:
            return 600.0
        out = 0.0
        for tg in job.task_groups:
            if tg.update is not None:
                out = max(out, tg.update.progress_deadline_s)
        return out or 600.0

    def _canaries_healthy(self, dep: Deployment) -> bool:
        # single source of truth with manual promotion's validation
        return not self.server._unhealthy_canary_groups(dep)

    # ----------------------------------------------------------- actions
    def _create_eval(self, dep: Deployment, trigger: str) -> None:
        job = self.server.store.job_by_id(dep.namespace, dep.job_id)
        if job is None or job.stopped():
            return
        self.server.upsert_evals([Evaluation(
            namespace=dep.namespace, job_id=dep.job_id, type=job.type,
            priority=job.priority, triggered_by=trigger,
            deployment_id=dep.id, status=EVAL_STATUS_PENDING)])

    def _succeed(self, dep: Deployment) -> None:
        self.server.apply_deployment_status_update(
            DeploymentStatusUpdate(
                deployment_id=dep.id,
                status=DEPLOYMENT_STATUS_SUCCESSFUL,
                status_description=DEPLOYMENT_DESC_SUCCESSFUL),
            mark_stable=(dep.namespace, dep.job_id, dep.job_version))
        with self._cv:
            self._state.pop(dep.id, None)

    def _fail(self, dep: Deployment, desc: str) -> None:
        """Fail the deployment; auto-revert to the latest stable job
        version when the update stanza asks for it
        (reference: deployment_watcher.go FailDeployment + the
        auto-revert path in watchers' handleAllocUpdate)."""
        rollback_job = None
        if any(s.auto_revert for s in dep.task_groups.values()):
            rollback_job = self._latest_stable_job(dep)
        # same-spec guard (reference: deployment_watcher.go:357
        # FailDeployment rollback skips when the stable spec equals the
        # current one) — otherwise a failed re-revert loops forever
        if rollback_job is not None:
            current = self.server.store.job_by_id(dep.namespace,
                                                  dep.job_id)
            from ..state.store import StateStore
            if current is not None and \
                    not StateStore._job_spec_changed(current, rollback_job):
                rollback_job = None
        if rollback_job is not None:
            desc += DESC_AUTO_REVERT_SUFFIX.format(rollback_job.version)
        self.server.apply_deployment_status_update(DeploymentStatusUpdate(
            deployment_id=dep.id, status=DEPLOYMENT_STATUS_FAILED,
            status_description=desc))
        with self._cv:
            self._state.pop(dep.id, None)
        if rollback_job is not None:
            self.server.revert_job(rollback_job)
        else:
            self._create_eval(dep, EVAL_TRIGGER_DEPLOYMENT_WATCHER)

    def _latest_stable_job(self, dep: Deployment):
        """Newest job version marked stable, older than the deploying one
        (reference: state JobVersionsByID + latestStableVersion)."""
        versions = self.server.store.job_versions(dep.namespace, dep.job_id)
        stable = [j for j in versions
                  if j.stable and j.version != dep.job_version]
        if not stable:
            return None
        return max(stable, key=lambda j: j.version)
