"""Scheduler worker: dequeue evals, invoke the scheduler, submit plans.

Reference: nomad/worker.go — run loop :105, dequeueEvaluation :142,
snapshotMinIndex wait :228, invokeScheduler :244, SubmitPlan :277 with
refresh-on-partial-commit :309. The worker is also the scheduler's
Planner (scheduler/scheduler.go:106).
"""
from __future__ import annotations

import logging
import threading
from typing import List, Optional, Tuple

from ..scheduler.base import new_scheduler
from ..structs import Evaluation, Plan, PlanResult

DEQUEUE_TIMEOUT_S = 0.2

_log = logging.getLogger(__name__)


class Worker(threading.Thread):
    def __init__(self, server, sched_types: List[str], index: int = 0):
        super().__init__(daemon=True)
        self.server = server
        self.sched_types = list(sched_types)
        #: worker index doubles as the broker home shard: worker i
        #: drains shard i % S first, so at N == S workers each shard has
        #: a dedicated drainer and dequeues never contend on one lock
        self.index = index
        self._shutdown = threading.Event()
        self.paused = threading.Event()
        self._solver = None
        self._solver_lock = threading.Lock()
        #: optional parallel.sharded.ElasticMeshSupervisor: node-update
        #: evals feed the elastic mesh's fail/recover state machine
        #: (ISSUE 8) — the scheduler-plane recovery trigger next to the
        #: serf-plane gossip callbacks
        self.mesh_supervisor = None

    def fleet_solver(self):
        """One Solver per worker, store-attached: its tensorizer's
        computed-class memo is shared across the fused batch, and its
        resident cluster world advances by changesets (plan-apply feed
        below + the store change log) instead of re-packing the world
        per eval.  Locked: the HTTP plan endpoint reaches in from its
        own thread for the what-if plan_view (ISSUE 7)."""
        with self._solver_lock:
            if self._solver is None:
                from ..solver.solve import Solver
                self._solver = Solver(store=self.server.store)
            return self._solver

    def shutdown(self) -> None:
        self._shutdown.set()

    def run(self) -> None:
        import time as _t

        from ..utils.metrics import global_metrics as _m
        while not self._shutdown.is_set():
            broker = self.server.broker
            serving = getattr(self.server, "serving", None)
            if self.paused.is_set() and \
                    broker.ready_count() <= self._max_batch():
                # Soft pause (leader CPU hygiene, reference:
                # leader.go:206-212): unlike the reference there are no
                # follower workers to absorb load in this architecture,
                # so a paused worker still wakes while the broker backs
                # up beyond one batch and returns to idle once drained.
                self._shutdown.wait(0.05)
                continue
            target = self._target_batch(serving, broker)
            batch = broker.dequeue_batch(
                self.sched_types, target, DEQUEUE_TIMEOUT_S,
                home=self.index)
            if not batch:
                # idle tick: readmit shed work once the queue drains
                self._readmit_tick(serving)
                continue
            if len(batch) > 1:
                # hold every member's redelivery deadline for the
                # duration of the fused work (see process_fleet, which
                # re-pauses idempotently): an express-lane solve or a
                # slow fused batch must not trigger spurious nack
                # redelivery for the members still waiting their turn
                broker.pause_nack_batch(
                    [(ev.id, token) for ev, token in batch])
            if serving is not None:
                # brownout: degrade the solve wave budget while the
                # queue is saturated (leftovers retry via the normal
                # blocked/requeue path); restore costs one cached
                # compile variant
                self.fleet_solver().set_degraded(
                    serving.admission.brownout_active())
            t0 = _t.monotonic()
            fused = False
            try:
                fused = self._run_batch(serving, batch)
            except Exception as exc:
                # a poisoned eval must not kill the worker; the nack path
                # redelivers it until the delivery limit parks it — but
                # the failure must be visible (ROBUST701): a storm of
                # silent nacks looks exactly like a healthy idle worker
                _log.warning("batch of %d eval(s) failed: %s",
                             len(batch), exc)
                _m.incr_counter("worker.batch_error")
                for ev, token in batch:
                    self.server.broker.nack(ev.id, token)
            if serving is not None:
                wall = _t.monotonic() - t0
                if not fused:
                    # fused rounds feed the sizing model their DEVICE
                    # stage from fleet_finish (note_device_solve): under
                    # pipelining the round wall double-counts the
                    # previous round's occupancy and would over-drain
                    # the close rule
                    serving.solve_model.observe(len(batch), wall)
                # SLO burn-rate accounting + the first explicit-bucket
                # histogram users (ISSUE 15): batch solve latency on
                # the latency bounds, batch size on pow2 count bounds
                serving.observe_batch(len(batch), wall)
                _m.observe_hist("worker.solve_latency_s", wall)
                _m.observe_hist("worker.batch_size", float(len(batch)),
                                buckets=(1, 2, 4, 8, 16, 32, 64, 128,
                                         256, 512))
                _m.set_gauge("serving.last_target_batch", float(target))
                _m.set_gauge(
                    "serving.brownout",
                    1.0 if serving.admission.brownout_active() else 0.0)
                self._readmit_tick(serving)

    def _max_batch(self) -> int:
        serving = getattr(self.server, "serving", None)
        if serving is not None and serving.adaptive:
            return serving.max_batch
        return self.server.batch_size

    def _target_batch(self, serving, broker) -> int:
        """Adaptive micro-batch sizing (serving tier): queue depth +
        oldest ready age + the EWMA solve-time model pick the largest
        batch that keeps age + predicted solve inside the SLO budget.
        Falls back to the fixed batch_size when the tier is disabled."""
        if serving is None or not serving.adaptive:
            return self.server.batch_size
        return serving.batch_controller.target_batch(
            broker.ready_count(), broker.oldest_ready_age())

    def _run_batch(self, serving, batch) -> bool:
        """Run one dequeued batch; returns True when the fused
        (coordinator / process_fleet) path handled the bulk lane, i.e.
        the sizing model was already fed device time by fleet_finish."""
        from ..utils.tracing import global_tracer as _tr
        if len(batch) == 1:
            _tr.event(batch[0][0].id, "worker.batch", batch_size=1,
                      lane="single")
            self._process(*batch[0])
            return False
        express, bulk = [], []
        bypass = serving.bypass_priority if serving is not None else None
        for ev, token in batch:
            if bypass is not None and ev.priority >= bypass:
                express.append((ev, token))
            else:
                bulk.append((ev, token))
        for ev, _tok in express:
            _tr.event(ev.id, "worker.batch", batch_size=len(batch),
                      lane="express")
        for ev, _tok in bulk:
            _tr.event(ev.id, "worker.batch", batch_size=len(batch),
                      lane="bulk" if len(bulk) > 1 else "single")
        # bypass lane: interactive/high-priority evals solve singly
        # FIRST (the in-process host path for small clusters — one
        # tunnel round trip), ahead of the fused bulk solve
        for ev, token in express:
            self._process(ev, token)
        if len(bulk) == 1:
            self._process(*bulk[0])
            return False
        elif bulk:
            coordinator = getattr(self.server, "solve_coordinator", None)
            if coordinator is not None:
                # cross-worker fusion: park on the coordinator so this
                # batch rides one combined device wave with whatever the
                # other workers dequeued (errors re-raise here and the
                # run-loop nack path owns our evals)
                coordinator.submit(self, bulk)
            else:
                from ..scheduler.fleet import process_fleet
                process_fleet(self.server, self, bulk)
            return True
        return False

    def _readmit_tick(self, serving) -> None:
        """Pop admission-shed evals back into the broker once the queue
        has drained below the low watermark (restore-on-drain)."""
        if serving is None:
            return
        quota = serving.admission.readmit_quota(
            self.server.broker.ready_count(),
            batch=serving.max_batch)
        if quota <= 0:
            return
        for ev in self.server.blocked_evals.pop_shed(quota):
            self.server.broker.enqueue(ev)

    def _process(self, ev: Evaluation, token: str) -> None:
        import time as _t

        from ..utils.metrics import global_metrics as _m
        server = self.server
        _m.incr_counter("worker.dequeue_eval")
        # the raft catch-up + solve + plan wait can exceed the nack
        # timeout; hold the timer while we own the eval
        server.broker.pause_nack_timeout(ev.id, token)
        # wait for local state to reach the eval's creation point
        # (reference metric: nomad.worker.wait_for_index)
        from ..utils.tracing import global_tracer as _tr
        wait_index = max(ev.modify_index, ev.snapshot_index)
        t0 = _t.monotonic()
        with _tr.stage(ev.id, "worker.wait_index", index=wait_index):
            server.store.wait_for_index(wait_index, timeout=5.0)
        _m.measure_since("worker.wait_for_index", t0)
        if self.mesh_supervisor is not None and ev.node_id:
            from ..structs import EVAL_TRIGGER_NODE_UPDATE
            if ev.triggered_by == EVAL_TRIGGER_NODE_UPDATE:
                # recovery trigger (ISSUE 8): a mesh-host node going
                # down fails its shard BEFORE this eval solves, so the
                # solve runs at degraded width instead of stalling on a
                # dead shard; its return to ready triggers the rejoin
                node = server.store.snapshot().node_by_id(ev.node_id)
                if node is not None:
                    self.mesh_supervisor.note_node_event(ev.node_id,
                                                         node.status)
        _invoke_t0 = _t.monotonic()
        try:
            from ..structs import JOB_TYPE_CORE
            if ev.type == JOB_TYPE_CORE:
                # administrative GC runs against a snapshot and reaps
                # through the server (worker.go:258, core_sched.go:46)
                from ..scheduler.core import CoreScheduler
                CoreScheduler(server, server.store.snapshot()).process(ev)
                err = None
            else:
                sched = new_scheduler(ev.type, server.store, self,
                                      solver=self.fleet_solver())
                err = sched.process(ev)
        except Exception as e:
            # record the failure on the eval so a parked (delivery-limited)
            # eval isn't restored as pending after a leader restart
            import copy
            from ..structs import EVAL_STATUS_FAILED
            failed = copy.copy(ev)
            failed.status = EVAL_STATUS_FAILED
            failed.status_description = f"scheduler error: {e}"
            server.upsert_evals([failed])
            server.broker.nack(ev.id, token)
            return
        finally:
            # reference metric: nomad.worker.invoke_scheduler_<type>
            _m.measure_since(f"worker.invoke_scheduler_{ev.type}",
                             _invoke_t0)
        if err is not None:
            server.broker.nack(ev.id, token)
        else:
            server.broker.ack(ev.id, token)

    # ---------------------------------------------------- Planner interface
    def submit_plan(self, plan: Plan
                    ) -> Tuple[Optional[PlanResult], Optional[object]]:
        import time as _t

        from ..utils.metrics import global_metrics as _m
        from ..utils.tracing import global_tracer as _tr
        t0 = _t.monotonic()
        sp = _tr.stage(plan.eval_id, "plan.submit",
                       n_alloc=sum(len(v) for v in
                                   plan.node_allocation.values()),
                       n_stop=sum(len(v) for v in
                                  plan.node_update.values()))
        pending = self.server.plan_queue.enqueue(plan)
        if pending is None:
            sp.end(outcome="queue_disabled")
            return None, None
        result, err = pending.future.wait(30.0)
        # reference metric: nomad.worker.submit_plan (p50/p99 plan-submit
        # latency — the BASELINE.md headline latency metric)
        _m.measure_since("worker.submit_plan", t0)
        if err is not None or result is None:
            sp.end(outcome=f"error: {err}" if err else "no result")
            return None, None
        sp.end(outcome="applied", alloc_index=result.alloc_index,
               refresh_index=result.refresh_index)
        # feed the applied changeset into the solver's resident world:
        # the next eval's solve starts from already-advanced tensors
        # (the change-log sync then dedups these same writes)
        if self._solver is not None:
            self._solver.note_plan_result(plan, result)
        if result.refresh_index:
            # partial commit: catch up past the conflicting writes and hand
            # the scheduler a fresh snapshot to retry against
            self.server.store.wait_for_index(result.refresh_index,
                                             timeout=5.0)
            return result, self.server.store.snapshot()
        return result, None

    def update_eval(self, ev: Evaluation) -> None:
        self.server.upsert_evals([ev])

    def create_eval(self, ev: Evaluation) -> None:
        self.server.upsert_evals([ev])

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.blocked_evals.block(ev)
