"""Leader-only periodic (cron) job dispatcher.

Tracks periodic jobs, computes each one's next launch from its cron spec,
and at the launch time derives a child job `<id>/periodic-<unix>` and
registers it (which creates the eval that actually schedules it).
Reference: nomad/periodic.go — PeriodicDispatch, Add/Remove, run loop,
`job.Periodic.Next` :228, derived jobs + `periodic_launch` table,
prohibit_overlap via ChildrenSummary.
"""
from __future__ import annotations

import copy
import heapq
import logging
import threading
import time as _time
from datetime import datetime, timezone
from typing import Dict, List, Optional, Tuple

_log = logging.getLogger(__name__)

from ..structs import Job
from ..utils.cron import Cron, CronParseError

PERIODIC_LAUNCH_SUFFIX = "/periodic-"


def _job_tz(job: Job):
    """Periodic specs evaluate in UTC unless the job names a time_zone
    (reference: structs.go PeriodicConfig.GetLocation) — never the
    server-local zone, which would shift launches with host TZ."""
    name = getattr(job.periodic, "timezone", "") or "UTC"
    if name.upper() in ("UTC", "LOCAL", ""):
        return timezone.utc
    try:
        from zoneinfo import ZoneInfo
        return ZoneInfo(name)
    except Exception:
        _log.warning("periodic job %s/%s: unknown time_zone %r, "
                     "falling back to UTC", job.namespace, job.id, name)
        return timezone.utc


def next_launch(job: Job, after: float) -> Optional[float]:
    """Next cron fire time for a periodic job, as a unix timestamp."""
    if job.periodic is None or not job.periodic.enabled:
        return None
    try:
        cron = Cron(job.periodic.spec)
    except CronParseError:
        return None
    dt = datetime.fromtimestamp(after, tz=_job_tz(job))
    # DST fall-back can make a "later" wall-clock time an EARLIER instant
    # (the repeated hour, fold=0); keep advancing until the launch is
    # strictly in the future so the dispatcher never fires a burst of
    # stale launches (≤62 steps covers the repeated hour at minute grain)
    for _ in range(62):
        nxt = cron.next(dt)
        if nxt is None:
            return None
        if nxt.timestamp() > after:
            return nxt.timestamp()
        dt = nxt
    return None


def derive_job(job: Job, launch: float) -> Job:
    """The child job actually scheduled at a launch (periodic.go derivedJob):
    a copy with the periodic config stripped and the parent recorded."""
    child = copy.deepcopy(job)
    child.id = f"{job.id}{PERIODIC_LAUNCH_SUFFIX}{int(launch)}"
    child.parent_id = job.id
    child.periodic = None
    return child


class PeriodicDispatcher:
    def __init__(self, server):
        self.server = server
        self._tracked: Dict[Tuple[str, str], Job] = {}
        self._heap: List[Tuple[float, Tuple[str, str]]] = []
        self._cv = threading.Condition()
        self._enabled = False
        self._runner: Optional[threading.Thread] = None

    # ------------------------------------------------------------ control
    def set_enabled(self, enabled: bool) -> None:
        runner = None
        with self._cv:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            if enabled:
                # thread handle guarded by _cv (nomadlint LOCK301)
                self._runner = threading.Thread(target=self._run, daemon=True)
                self._runner.start()
            else:
                self._tracked.clear()
                self._heap.clear()
                runner, self._runner = self._runner, None
                self._cv.notify_all()
        if runner is not None:
            runner.join(timeout=1.0)

    def add(self, job: Job) -> None:
        """Track (or retrack) a periodic job; untracks if it stopped being
        periodic / was stopped (reference periodic.go Add)."""
        key = (job.namespace, job.id)
        with self._cv:
            if not self._enabled:
                return
            if job.periodic is None or not job.periodic.enabled \
                    or job.stopped():
                self._tracked.pop(key, None)
                return
            self._tracked[key] = job
            nxt = next_launch(job, _time.time())
            if nxt is not None:
                heapq.heappush(self._heap, (nxt, key))
                self._cv.notify_all()

    def remove(self, namespace: str, job_id: str) -> None:
        with self._cv:
            self._tracked.pop((namespace, job_id), None)

    def tracked(self) -> List[Job]:
        with self._cv:
            return list(self._tracked.values())

    def force_launch(self, namespace: str, job_id: str) -> Optional[Job]:
        """Launch now regardless of schedule (`nomad job periodic force`)."""
        with self._cv:
            job = self._tracked.get((namespace, job_id))
        if job is None:
            return None
        return self._launch(job, _time.time())

    # -------------------------------------------------------------- loop
    def _run(self) -> None:
        while True:
            launch_job: Optional[Job] = None
            launch_time = 0.0
            with self._cv:
                if not self._enabled:
                    return
                now = _time.time()
                while self._heap and self._heap[0][0] <= now:
                    when, key = heapq.heappop(self._heap)
                    job = self._tracked.get(key)
                    if job is None:
                        continue
                    # skip stale heap entries from retracking
                    launch_job, launch_time = job, when
                    # schedule the following launch before running this one
                    nxt = next_launch(job, max(now, when))
                    if nxt is not None:
                        heapq.heappush(self._heap, (nxt, key))
                    break
                if launch_job is None:
                    wait = 0.5
                    if self._heap:
                        wait = min(wait, max(self._heap[0][0] - now, 0.01))
                    self._cv.wait(wait)
                    continue
            self._launch(launch_job, launch_time)

    def _launch(self, job: Job, launch: float) -> Optional[Job]:
        if job.periodic and job.periodic.prohibit_overlap:
            if self._has_running_child(job):
                return None
        child = derive_job(job, launch)
        self.server.register_job(child)
        self.server.record_periodic_launch(job.namespace, job.id, launch)
        return child

    def _has_running_child(self, job: Job) -> bool:
        prefix = f"{job.id}{PERIODIC_LAUNCH_SUFFIX}"
        for j in self.server.store.jobs_by_namespace(job.namespace):
            if j.parent_id == job.id and j.id.startswith(prefix) \
                    and not j.stopped() and j.status != "dead":
                return True
        return False
