"""Prefix search over the ID spaces.

Reference: nomad/search_endpoint.go — fuzzy/prefix matches across
jobs, evals, allocs, nodes and deployments, truncated per context.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

TRUNCATE_LIMIT = 20     # reference: search_endpoint.go truncateLimit

CONTEXTS = ("jobs", "evals", "allocs", "nodes", "deployment")
ALL_CONTEXT = "all"


def search(store, prefix: str, context: str = ALL_CONTEXT,
           namespace: str = "default"
           ) -> Tuple[Dict[str, List[str]], Dict[str, bool]]:
    """Returns (matches per context, truncation flags per context)."""
    contexts = CONTEXTS if context in ("", ALL_CONTEXT) else (context,)
    matches: Dict[str, List[str]] = {}
    truncations: Dict[str, bool] = {}
    for ctx in contexts:
        ids = _ids_for(store, ctx, namespace)
        hit = sorted(i for i in ids if i.startswith(prefix))
        truncations[ctx] = len(hit) > TRUNCATE_LIMIT
        matches[ctx] = hit[:TRUNCATE_LIMIT]
    return matches, truncations


def _ids_for(store, ctx: str, namespace: str) -> List[str]:
    if ctx == "jobs":
        return [j.id for j in store.jobs()
                if j.namespace == namespace]
    if ctx == "evals":
        return [e.id for e in store.evals()
                if e.namespace == namespace]
    if ctx == "allocs":
        return [a.id for a in store.allocs()
                if a.namespace == namespace]
    if ctx == "nodes":
        return [n.id for n in store.nodes()]       # nodes are global
    if ctx == "deployment":
        return [d.id for d in store.deployments()
                if d.namespace == namespace]
    raise ValueError(f"unknown search context {ctx!r}")
