"""EvalBroker: leader-only, at-least-once priority work queue for evals.

Semantics mirror nomad/eval_broker.go — per-scheduler-type priority heaps
(:65), per-job serialization so at most one eval per job is in flight
(:277-297), blocking Dequeue (:329), Ack/Nack with nack-timer redelivery
and a delivery limit that shunts flapping evals to a `_failed` queue
(:23, :531, :595), and delayed evals via a wait-until heap (:89, :751).

`dequeue_batch` drains up to K ready evals — each for a different job, by
construction of the per-job serialization — and is the coalescing point
for the fused multi-eval device solve (SURVEY §2.5); the stock worker
loop dequeues singly, matching the reference.  K is sized per dequeue by
the serving tier's BatchController (server/serving.py) from the queue
depth and the oldest ready eval's age, which the broker tracks here.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

from ..structs import EVAL_STATUS_PENDING, Evaluation
from ..utils.ids import generate_uuid
from ..utils.tracing import global_tracer as _tr

FAILED_QUEUE = "_failed"
DEFAULT_NACK_DELAY_S = 5.0
DEFAULT_INITIAL_NACK_DELAY_S = 1.0
DEFAULT_MAX_NACK_DELAY_S = 60.0
DEFAULT_DELIVERY_LIMIT = 3


class _Heap:
    """Max-priority heap with FIFO tie-break."""

    def __init__(self) -> None:
        self._h: List[tuple] = []
        self._count = itertools.count()

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._h, (-ev.priority, next(self._count), ev))

    def pop(self) -> Optional[Evaluation]:
        if not self._h:
            return None
        return heapq.heappop(self._h)[2]

    def peek_priority(self) -> Optional[int]:
        if not self._h:
            return None
        return -self._h[0][0]

    def __len__(self) -> int:
        return len(self._h)


class _Unack:
    def __init__(self, ev: Evaluation, token: str):
        self.eval = ev
        self.token = token
        self.nack_timer: Optional[threading.Timer] = None


class EvalBroker:
    def __init__(self, nack_delay_s: float = DEFAULT_NACK_DELAY_S,
                 initial_nack_delay_s: float = DEFAULT_INITIAL_NACK_DELAY_S,
                 delivery_limit: int = DEFAULT_DELIVERY_LIMIT,
                 max_nack_delay_s: float = DEFAULT_MAX_NACK_DELAY_S,
                 nack_jitter_seed: int = 0xACED):
        self._lock = threading.Condition()
        self._enabled = False
        self._ready: Dict[str, _Heap] = {}
        self._unack: Dict[str, _Unack] = {}
        self._job_evals: Dict[Tuple[str, str], str] = {}   # (ns, job) -> eval
        self._blocked: Dict[Tuple[str, str], _Heap] = {}   # per-job backlog
        self._requeue: Dict[str, Evaluation] = {}  # token-gated re-enqueue
        self._waiting: Dict[str, Evaluation] = {}  # delayed (wait_until)
        self._delay_heap: List[tuple] = []
        self._dequeues = 0
        self._nacks = 0
        # eval id -> monotonic enqueue time while sitting in a ready
        # heap: feeds oldest_ready_age(), the BatchController's
        # SLO-budget close rule input (insertion order ~ enqueue order,
        # so the first live entry is the oldest)
        self._ready_since: Dict[str, float] = {}
        self.nack_delay_s = nack_delay_s
        self.initial_nack_delay_s = initial_nack_delay_s
        self.max_nack_delay_s = max_nack_delay_s
        self.delivery_limit = delivery_limit
        self._deliveries: Dict[str, int] = {}
        # seeded so chaos/replay runs see the same redelivery schedule
        import random as _random
        self._nack_rng = _random.Random(nack_jitter_seed)
        self._delay_thread: Optional[threading.Thread] = None
        self._stop_delay = threading.Event()

    # ------------------------------------------------------------ lifecycle
    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            prev = self._enabled
            self._enabled = enabled
            if enabled and not prev:
                # thread handle guarded by _lock (the watcher's first
                # action is to take it, so starting under the lock just
                # briefly blocks the new thread)
                self._stop_delay.clear()
                self._delay_thread = threading.Thread(
                    target=self._run_delayed_watcher, daemon=True)
                self._delay_thread.start()
        if prev and not enabled:
            self.flush()
        if not enabled:
            self._stop_delay.set()

    @property
    def enabled(self) -> bool:
        with self._lock:    # guarded by _lock: see set_enabled
            return self._enabled

    def ready_count(self) -> int:
        """Evals ready for dequeue right now (not delayed/unacked)."""
        with self._lock:
            return sum(len(h) for h in self._ready.values())

    def oldest_ready_age(self) -> float:
        """Seconds the oldest currently-ready eval has been waiting.
        Dict insertion order tracks enqueue order, so the first live
        entry is the oldest — O(1), called per dequeue by the
        BatchController."""
        with self._lock:
            for t0 in self._ready_since.values():
                return _time.monotonic() - t0
            return 0.0

    def export_metrics(self) -> None:
        """Publish queue-shape gauges through the global metrics path
        (surfaced at /v1/metrics next to the worker.dequeue_eval
        counters).  Called by the worker loop each iteration — cheap:
        one lock hold, no allocation beyond the per-queue dict walk."""
        from ..utils.metrics import global_metrics as _m
        with self._lock:
            ready = {q: len(h) for q, h in self._ready.items()}
            unacked = len(self._unack)
            waiting = len(self._waiting)
            blocked = sum(len(h) for h in self._blocked.values())
            oldest = 0.0
            for t0 in self._ready_since.values():
                oldest = _time.monotonic() - t0
                break
            # per-eval delivery counts: only evals past their first
            # delivery (the interesting, bounded set — at most
            # delivery_limit redeliveries each before parking), so
            # gauge cardinality stays proportional to flapping evals,
            # not throughput; the registry's namespace cap absorbs
            # pathological storms as metrics.overflow
            redelivered = {eid: n for eid, n in self._deliveries.items()
                           if n > 1}
        _m.set_gauge("broker.ready_count", float(sum(ready.values())))
        _m.set_gauge("broker.redelivering", float(len(redelivered)))
        for eid, n in redelivered.items():
            _m.set_gauge(f"broker.deliveries.{eid}", float(n))
        _m.set_gauge("broker.oldest_ready_age_s", oldest)
        _m.set_gauge("broker.unacked", float(unacked))
        _m.set_gauge("broker.waiting", float(waiting))
        _m.set_gauge("broker.job_blocked", float(blocked))
        for q, n in ready.items():
            _m.set_gauge(f"broker.ready.{q}", float(n))

    def flush(self) -> None:
        with self._lock:
            for u in self._unack.values():
                if u.nack_timer:
                    u.nack_timer.cancel()
            self._ready.clear()
            self._unack.clear()
            self._job_evals.clear()
            self._blocked.clear()
            self._requeue.clear()
            self._waiting.clear()
            self._delay_heap.clear()
            self._deliveries.clear()
            self._ready_since.clear()
            self._lock.notify_all()

    # ------------------------------------------------------------- enqueue
    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(ev, ev.type)

    def enqueue_all(self, evals: List[Tuple[Evaluation, str]]) -> None:
        """Enqueue (eval, token) pairs; a matching token for an unacked
        eval defers the re-enqueue until that eval is acked."""
        with self._lock:
            for ev, token in evals:
                if token:
                    self._process_waiting_enqueue_locked(ev, token)
                else:
                    self._enqueue_locked(ev, ev.type)

    def _process_waiting_enqueue_locked(self, ev: Evaluation,
                                        token: str) -> None:
        u = self._unack.get(ev.id)
        if u is not None and u.token == token:
            self._requeue[ev.id] = ev
        else:
            self._enqueue_locked(ev, ev.type)

    def _enqueue_locked(self, ev: Evaluation, queue: str) -> None:
        if not self._enabled:
            return
        if ev.id in self._unack or ev.id in self._waiting:
            return
        if ev.wait_until and ev.wait_until > _time.time():
            self._waiting[ev.id] = ev
            heapq.heappush(self._delay_heap, (ev.wait_until, ev.id))
            self._lock.notify_all()
            return
        namespaced = (ev.namespace, ev.job_id)
        if queue != FAILED_QUEUE and ev.job_id:
            holder = self._job_evals.get(namespaced)
            if holder is not None and holder != ev.id:
                self._blocked.setdefault(namespaced, _Heap()).push(ev)
                _tr.event(ev.id, "broker.job_blocked", queue=queue,
                          holder=holder)
                return
            self._job_evals[namespaced] = ev.id
        self._ready.setdefault(queue, _Heap()).push(ev)
        self._ready_since[ev.id] = _time.monotonic()
        _tr.event(ev.id, "broker.enqueue", queue=queue)
        self._lock.notify_all()

    # ------------------------------------------------------------- dequeue
    def dequeue(self, sched_types: Sequence[str], timeout: float = 0.0
                ) -> Tuple[Optional[Evaluation], str]:
        deadline = _time.monotonic() + timeout
        with self._lock:
            while True:
                ev, age = self._dequeue_locked(sched_types)
                if ev is not None:
                    token = generate_uuid()
                    u = _Unack(ev, token)
                    self._unack[ev.id] = u
                    self._deliveries[ev.id] = \
                        self._deliveries.get(ev.id, 0) + 1
                    self._dequeues += 1
                    self._start_nack_timer(u)
                    _tr.event(ev.id, "broker.dequeue",
                              queue_age_s=round(age, 6),
                              delivery=self._deliveries[ev.id])
                    return ev, token
                remain = deadline - _time.monotonic()
                if remain <= 0 or not self._enabled:
                    return None, ""
                self._lock.wait(remain)

    def dequeue_batch(self, sched_types: Sequence[str], max_batch: int,
                      timeout: float = 0.0
                      ) -> List[Tuple[Evaluation, str]]:
        """Drain up to max_batch ready evals (the TPU coalescing point).
        Blocks for the first eval only; the rest are taken opportunistically."""
        first, token = self.dequeue(sched_types, timeout)
        if first is None:
            return []
        out = [(first, token)]
        while len(out) < max_batch:
            ev, tok = self.dequeue(sched_types, 0.0)
            if ev is None:
                break
            out.append((ev, tok))
        # dequeue-batch size histogram (p50/p99 via the metrics
        # reservoir) — the observability face of the BatchController
        from ..utils.metrics import global_metrics as _m
        _m.add_sample("broker.dequeue_batch_size", float(len(out)))
        return out

    def _dequeue_locked(self, sched_types: Sequence[str]
                        ) -> Tuple[Optional[Evaluation], float]:
        """Returns (eval, ready-queue age seconds)."""
        best_q, best_pri = None, None
        for q in sched_types:
            h = self._ready.get(q)
            if h is None or not len(h):
                continue
            pri = h.peek_priority()
            if best_pri is None or pri > best_pri:
                best_q, best_pri = q, pri
        if best_q is None:
            return None, 0.0
        ev = self._ready[best_q].pop()
        age = 0.0
        if ev is not None:
            t0 = self._ready_since.pop(ev.id, None)
            if t0 is not None:
                age = _time.monotonic() - t0
        return ev, age

    def _start_nack_timer(self, u: _Unack) -> None:
        t = threading.Timer(self.nack_delay_s,
                            self._nack_timeout, args=(u.eval.id, u.token))
        t.daemon = True
        u.nack_timer = t
        t.start()

    def _nack_timeout(self, eval_id: str, token: str) -> None:
        with self._lock:
            u = self._unack.get(eval_id)
            if u is None or u.token != token:
                return
        self.nack(eval_id, token)

    def pause_nack_timeout(self, eval_id: str, token: str) -> Optional[str]:
        """Stop the redelivery timer while the holder does long work
        (reference: eval_broker PauseNackTimeout, used while waiting on
        raft / the fused solve). The holder must still ack or nack."""
        with self._lock:
            u = self._unack.get(eval_id)
            if u is None or u.token != token:
                return "token mismatch"
            if u.nack_timer:
                u.nack_timer.cancel()
                u.nack_timer = None
            return None

    def resume_nack_timeout(self, eval_id: str, token: str) -> Optional[str]:
        with self._lock:
            u = self._unack.get(eval_id)
            if u is None or u.token != token:
                return "token mismatch"
            self._start_nack_timer(u)
            return None

    # ------------------------------------------------------------ ack/nack
    def ack(self, eval_id: str, token: str) -> Optional[str]:
        with self._lock:
            u = self._unack.get(eval_id)
            if u is None or u.token != token:
                return "token mismatch"
            if u.nack_timer:
                u.nack_timer.cancel()
            del self._unack[eval_id]
            self._deliveries.pop(eval_id, None)
            ev = u.eval
            _tr.event(eval_id, "broker.ack")
            self._release_job_slot_locked(ev, eval_id)
            requeue = self._requeue.pop(eval_id, None)
            if requeue is not None:
                self._enqueue_locked(requeue, requeue.type)
            return None

    def _release_job_slot_locked(self, ev: Evaluation,
                                 eval_id: str) -> None:
        """Free the job's serialization slot and promote its next blocked
        eval, if any."""
        namespaced = (ev.namespace, ev.job_id)
        if self._job_evals.get(namespaced) != eval_id:
            return
        del self._job_evals[namespaced]
        backlog = self._blocked.get(namespaced)
        if backlog is not None and len(backlog):
            nxt = backlog.pop()
            if not len(backlog):
                del self._blocked[namespaced]
            self._job_evals[namespaced] = nxt.id
            self._ready.setdefault(nxt.type, _Heap()).push(nxt)
            self._ready_since[nxt.id] = _time.monotonic()
            self._lock.notify_all()

    def nack(self, eval_id: str, token: str) -> Optional[str]:
        with self._lock:
            u = self._unack.get(eval_id)
            if u is None or u.token != token:
                return "token mismatch"
            if u.nack_timer:
                u.nack_timer.cancel()
            del self._unack[eval_id]
            self._requeue.pop(eval_id, None)
            self._nacks += 1
            from ..utils.metrics import global_metrics as _m
            _m.incr_counter("broker.nack")
            ev = u.eval
            # keep the per-job serialization slot held by the nacked eval
            # until it is acked (reference Nack semantics) so a newer eval
            # for the job can't jump ahead of the redelivery; the slot is
            # only freed when the eval is parked for the failed-eval reaper
            if self._deliveries.get(eval_id, 0) >= self.delivery_limit:
                self._release_job_slot_locked(ev, eval_id)
                # too many failed deliveries: park it for the leader reaper
                self._ready.setdefault(FAILED_QUEUE, _Heap()).push(ev)
                self._ready_since[ev.id] = _time.monotonic()
                _tr.event(eval_id, "broker.nack", parked=True,
                          deliveries=self._deliveries.get(eval_id, 0))
                self._lock.notify_all()
                return None
            # redeliver after a capped jittered exponential delay:
            # linear compounding barely separates a flapping eval from
            # healthy redeliveries, and unjittered delays re-collide a
            # burst of nacked evals at every retry (thundering herd)
            n = max(1, self._deliveries.get(eval_id, 1))
            delay = min(self.max_nack_delay_s,
                        self.initial_nack_delay_s * (2 ** (n - 1)))
            delay *= 0.5 + self._nack_rng.random() / 2.0
            _tr.event(eval_id, "broker.nack", parked=False,
                      deliveries=self._deliveries.get(eval_id, 0),
                      redeliver_delay_s=round(delay, 6))
            ev2 = ev
            deadline = _time.time() + delay
            self._waiting[ev2.id] = ev2
            heapq.heappush(self._delay_heap, (deadline, ev2.id))
            self._lock.notify_all()
            return None

    # ------------------------------------------------------ delayed watcher
    def _run_delayed_watcher(self) -> None:
        while not self._stop_delay.is_set():
            with self._lock:
                now = _time.time()
                wait = 0.1
                while self._delay_heap and self._delay_heap[0][0] <= now:
                    _, eid = heapq.heappop(self._delay_heap)
                    ev = self._waiting.pop(eid, None)
                    if ev is not None:
                        ev2 = ev
                        if ev2.wait_until:
                            import copy
                            ev2 = copy.copy(ev)
                            ev2.wait_until = 0.0
                        self._enqueue_locked(ev2, ev2.type)
                if self._delay_heap:
                    wait = min(wait, max(0.0,
                                         self._delay_heap[0][0] - now))
            self._stop_delay.wait(max(wait, 0.01))

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            oldest = 0.0
            for t0 in self._ready_since.values():
                oldest = _time.monotonic() - t0
                break
            return {
                "total_ready": sum(len(h) for h in self._ready.values()),
                "total_unacked": len(self._unack),
                "total_blocked": sum(len(h) for h in self._blocked.values()),
                "total_waiting": len(self._waiting),
                "by_scheduler": {q: len(h) for q, h in self._ready.items()},
                "dequeues": self._dequeues,
                "nacks": self._nacks,
                "oldest_ready_age_s": round(oldest, 6),
            }

    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            u = self._unack.get(eval_id)
            return u.token if u else None
