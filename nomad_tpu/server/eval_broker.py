"""EvalBroker: leader-only, at-least-once priority work queue for evals.

Semantics mirror nomad/eval_broker.go — per-scheduler-type priority heaps
(:65), per-job serialization so at most one eval per job is in flight
(:277-297), blocking Dequeue (:329), Ack/Nack with nack-timer redelivery
and a delivery limit that shunts flapping evals to a `_failed` queue
(:23, :531, :595), and delayed evals via a wait-until heap (:89, :751).

SHARDING (ISSUE 17): the broker is partitioned into S independent
shards keyed by crc32(namespace, job) — per-shard lock, ready heaps,
`_ready_since` insertion-order age tracking, job slots and nack
deadlines (a heap serviced by the broker's one delayed-watcher thread
— never a timer thread per eval).  A job maps to exactly one shard, so per-job serialization
holds by construction without any cross-shard coordination; evals
without a job route by eval id.  Dequeue starts at the caller's home
shard (its worker index) and steals from the other shards when the
home shard is dry, so no shard strands work.  One shard (the default)
is bit-identical to the pre-shard broker: same heap ordering, same
seeded nack-jitter schedule, same delivery-limit parking.

`dequeue_batch` drains up to K ready evals — each for a different job,
by construction of the per-job serialization — and is the coalescing
point for the fused multi-eval device solve (SURVEY §2.5); the stock
worker loop dequeues singly, matching the reference.  K is sized per
dequeue by the serving tier's BatchController (server/serving.py) from
the queue depth and the oldest ready eval's age, which the broker
tracks here.
"""
from __future__ import annotations

import heapq
import itertools
import os
import threading
import time as _time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..structs import EVAL_STATUS_PENDING, Evaluation
from ..utils.ids import generate_uuid
from ..utils.tracing import global_tracer as _tr

FAILED_QUEUE = "_failed"
DEFAULT_NACK_DELAY_S = 5.0
DEFAULT_INITIAL_NACK_DELAY_S = 1.0
DEFAULT_MAX_NACK_DELAY_S = 60.0
DEFAULT_DELIVERY_LIMIT = 3
#: shard count when neither the ctor nor NOMAD_TPU_BROKER_SHARDS says
#: otherwise — 1 keeps the reference (pre-shard) behavior bit-identical
DEFAULT_BROKER_SHARDS = 1


def _default_shards() -> int:
    try:
        return max(1, int(os.environ.get("NOMAD_TPU_BROKER_SHARDS",
                                         str(DEFAULT_BROKER_SHARDS))))
    except ValueError:
        return DEFAULT_BROKER_SHARDS


class _Heap:
    """Max-priority heap with FIFO tie-break."""

    def __init__(self) -> None:
        self._h: List[tuple] = []
        self._count = itertools.count()

    def push(self, ev: Evaluation) -> None:
        heapq.heappush(self._h, (-ev.priority, next(self._count), ev))

    def pop(self) -> Optional[Evaluation]:
        if not self._h:
            return None
        return heapq.heappop(self._h)[2]

    def peek_priority(self) -> Optional[int]:
        if not self._h:
            return None
        return -self._h[0][0]

    def __len__(self) -> int:
        return len(self._h)


class _Unack:
    __slots__ = ("eval", "token", "nack_deadline")

    def __init__(self, ev: Evaluation, token: str):
        self.eval = ev
        self.token = token
        # wall-clock redelivery deadline, or None while paused.  Armed
        # entries also sit in the shard's `_nack_heap`; a pause/ack/nack
        # invalidates lazily (the heap entry's deadline no longer
        # matches), so no per-eval timer thread ever exists — the
        # broker's single delayed-watcher services every deadline.
        self.nack_deadline: Optional[float] = None


class _Shard:
    """One broker partition: its own lock, ready heaps, job slots,
    unacked set, delay heap and nack-deadline heap.  All cross-thread entry
    points take `self._lock`; `_locked`-suffixed helpers document the
    caller already holds it.  Wake-ups for blocked dequeuers go through
    the owning broker's shared ready condition (`notify_ready`) — the
    shard lock is never held while waiting, only while mutating."""

    def __init__(self, broker: "EvalBroker", index: int,
                 nack_jitter_seed: int):
        self._broker = broker
        self.index = index
        self._lock = threading.Lock()
        self._ready: Dict[str, _Heap] = {}
        self._unack: Dict[str, _Unack] = {}
        self._job_evals: Dict[Tuple[str, str], str] = {}  # (ns, job) -> eval
        self._blocked: Dict[Tuple[str, str], _Heap] = {}  # per-job backlog
        self._requeue: Dict[str, Evaluation] = {}  # token-gated re-enqueue
        self._waiting: Dict[str, Evaluation] = {}  # delayed (wait_until)
        self._delay_heap: List[tuple] = []
        # (deadline, eval_id, token) redelivery deadlines for unacked
        # evals, serviced by the broker's delayed watcher.  Replaces the
        # per-eval threading.Timer of the pre-19 broker: at thousands of
        # dequeues/s the timer threads alone (create+start+cancel ~45µs
        # each, plus scheduler churn from the live-thread population)
        # were the worker-scaling ceiling.  Entries are append-only and
        # validated lazily against the _Unack's current deadline.
        self._nack_heap: List[tuple] = []
        self._dequeues = 0
        self._nacks = 0
        # eval id -> monotonic enqueue time while sitting in a ready
        # heap: feeds oldest_ready_age(), the BatchController's
        # SLO-budget close rule input (insertion order ~ enqueue order,
        # so the first live entry is the oldest)
        self._ready_since: Dict[str, float] = {}
        self._deliveries: Dict[str, int] = {}
        # seeded per shard so chaos/replay runs see the same redelivery
        # schedule; shard 0 keeps the exact pre-shard sequence
        import random as _random
        self._nack_rng = _random.Random(nack_jitter_seed + index)

    # ------------------------------------------------------------- enqueue
    def enqueue(self, ev: Evaluation) -> None:
        with self._lock:
            self._enqueue_locked(ev, ev.type)

    def enqueue_all(self, evals: List[Tuple[Evaluation, str]]) -> None:
        with self._lock:
            for ev, token in evals:
                if token:
                    self._process_waiting_enqueue_locked(ev, token)
                else:
                    self._enqueue_locked(ev, ev.type)

    def enqueue_batch(self, evals: List[Evaluation]) -> None:
        """Bulk enqueue under ONE lock hold with ONE dequeuer wakeup.
        Per-eval enqueue costs ~3x the heap push itself in lock and
        condition traffic; plan followups and saturated ingress arrive
        in bursts, so coalescing is the hot-path shape."""
        with self._lock:
            for ev in evals:
                self._enqueue_locked(ev, ev.type, notify=False)
        self._broker.notify_ready()

    def _process_waiting_enqueue_locked(self, ev: Evaluation,
                                        token: str) -> None:
        u = self._unack.get(ev.id)
        if u is not None and u.token == token:
            self._requeue[ev.id] = ev
        else:
            self._enqueue_locked(ev, ev.type)

    def _enqueue_locked(self, ev: Evaluation, queue: str,
                        notify: bool = True) -> None:
        if not self._broker.enabled_flag:
            return
        if ev.id in self._unack or ev.id in self._waiting:
            return
        if ev.wait_until and ev.wait_until > _time.time():
            self._waiting[ev.id] = ev
            heapq.heappush(self._delay_heap, (ev.wait_until, ev.id))
            return
        namespaced = (ev.namespace, ev.job_id)
        if queue != FAILED_QUEUE and ev.job_id:
            holder = self._job_evals.get(namespaced)
            if holder is not None and holder != ev.id:
                self._blocked.setdefault(namespaced, _Heap()).push(ev)
                _tr.event(ev.id, "broker.job_blocked", queue=queue,
                          holder=holder)
                return
            self._job_evals[namespaced] = ev.id
        self._ready.setdefault(queue, _Heap()).push(ev)
        self._ready_since[ev.id] = _time.monotonic()
        _tr.event(ev.id, "broker.enqueue", queue=queue, shard=self.index)
        if notify:
            self._broker.notify_ready()

    # ------------------------------------------------------------- dequeue
    def try_dequeue(self, sched_types: Sequence[str]
                    ) -> Tuple[Optional[Evaluation], str]:
        """Non-blocking: pop the best ready eval, register the unack and
        arm its nack deadline.  Returns (eval, token) or (None, "")."""
        out = self.try_dequeue_n(sched_types, 1)
        if not out:
            return None, ""
        return out[0]

    def try_dequeue_n(self, sched_types: Sequence[str], max_n: int
                      ) -> List[Tuple[Evaluation, str]]:
        """Non-blocking bulk dequeue: pop up to `max_n` ready evals
        under ONE lock hold (the fused-solve hot path — per-eval lock
        round trips at batch 128 cost more than the pops themselves)."""
        out: List[Tuple[Evaluation, str]] = []
        with self._lock:
            while len(out) < max_n:
                ev, age = self._dequeue_locked(sched_types)
                if ev is None:
                    break
                # shard index rides in the token so ack/nack route
                # without a broker-level eval->shard map (no shared
                # lock on the ack path)
                token = f"{self.index}.{generate_uuid()}"
                u = _Unack(ev, token)
                self._unack[ev.id] = u
                self._deliveries[ev.id] = \
                    self._deliveries.get(ev.id, 0) + 1
                self._dequeues += 1
                self._arm_nack_locked(u)
                _tr.event(ev.id, "broker.dequeue",
                          queue_age_s=round(age, 6),
                          delivery=self._deliveries[ev.id],
                          shard=self.index)
                out.append((ev, token))
        return out

    def _dequeue_locked(self, sched_types: Sequence[str]
                        ) -> Tuple[Optional[Evaluation], float]:
        """Returns (eval, ready-queue age seconds)."""
        best_q, best_pri = None, None
        for q in sched_types:
            h = self._ready.get(q)
            if h is None or not len(h):
                continue
            pri = h.peek_priority()
            if best_pri is None or pri > best_pri:
                best_q, best_pri = q, pri
        if best_q is None:
            return None, 0.0
        ev = self._ready[best_q].pop()
        age = 0.0
        if ev is not None:
            t0 = self._ready_since.pop(ev.id, None)
            if t0 is not None:
                age = _time.monotonic() - t0
        return ev, age

    def _arm_nack_locked(self, u: _Unack) -> None:
        """Arm (or re-arm) the redelivery deadline.  Caller holds the
        shard lock.  A prior heap entry for the same unack is not
        removed — it carries a different deadline and fails the lazy
        validation when it surfaces."""
        deadline = _time.time() + self._broker.nack_delay_s
        u.nack_deadline = deadline
        heapq.heappush(self._nack_heap, (deadline, u.eval.id, u.token))

    def pause_nack_timeout(self, eval_id: str,
                           token: str) -> Optional[str]:
        with self._lock:
            return self._pause_nack_locked(eval_id, token)

    def _pause_nack_locked(self, eval_id: str,
                           token: str) -> Optional[str]:
        u = self._unack.get(eval_id)
        if u is None or u.token != token:
            return "token mismatch"
        # the heap entry goes stale in place: the watcher skips any
        # entry whose deadline no longer matches the live unack
        u.nack_deadline = None
        return None

    def pause_nack_batch(self, pairs: List[Tuple[str, str]]
                         ) -> List[Optional[str]]:
        """Pause redelivery for many (eval_id, token) pairs under one
        lock hold; returns per-pair errors aligned with the input."""
        with self._lock:
            return [self._pause_nack_locked(eid, tok)
                    for eid, tok in pairs]

    def resume_nack_timeout(self, eval_id: str,
                            token: str) -> Optional[str]:
        with self._lock:
            u = self._unack.get(eval_id)
            if u is None or u.token != token:
                return "token mismatch"
            self._arm_nack_locked(u)
            return None

    # ------------------------------------------------------------ ack/nack
    def ack(self, eval_id: str, token: str) -> Optional[str]:
        with self._lock:
            return self._ack_locked(eval_id, token)

    def ack_batch(self, pairs: List[Tuple[str, str]]
                  ) -> List[Optional[str]]:
        """Ack many (eval_id, token) pairs under one lock hold; returns
        per-pair errors aligned with the input."""
        with self._lock:
            return [self._ack_locked(eid, tok) for eid, tok in pairs]

    def _ack_locked(self, eval_id: str, token: str) -> Optional[str]:
        u = self._unack.get(eval_id)
        if u is None or u.token != token:
            return "token mismatch"
        del self._unack[eval_id]
        self._deliveries.pop(eval_id, None)
        ev = u.eval
        _tr.event(eval_id, "broker.ack")
        self._release_job_slot_locked(ev, eval_id)
        requeue = self._requeue.pop(eval_id, None)
        if requeue is not None:
            self._enqueue_locked(requeue, requeue.type)
        return None

    def _release_job_slot_locked(self, ev: Evaluation,
                                 eval_id: str) -> None:
        """Free the job's serialization slot and promote its next
        blocked eval, if any."""
        namespaced = (ev.namespace, ev.job_id)
        if self._job_evals.get(namespaced) != eval_id:
            return
        del self._job_evals[namespaced]
        backlog = self._blocked.get(namespaced)
        if backlog is not None and len(backlog):
            nxt = backlog.pop()
            if not len(backlog):
                del self._blocked[namespaced]
            self._job_evals[namespaced] = nxt.id
            self._ready.setdefault(nxt.type, _Heap()).push(nxt)
            self._ready_since[nxt.id] = _time.monotonic()
            self._broker.notify_ready()

    def nack(self, eval_id: str, token: str) -> Optional[str]:
        with self._lock:
            return self._nack_locked(eval_id, token)

    def _nack_locked(self, eval_id: str, token: str) -> Optional[str]:
        """Nack body; the caller holds self._lock (the nack timer's
        check-then-act shares one hold with the requeue)."""
        u = self._unack.get(eval_id)
        if u is None or u.token != token:
            return "token mismatch"
        del self._unack[eval_id]
        self._requeue.pop(eval_id, None)
        self._nacks += 1
        from ..utils.metrics import global_metrics as _m
        _m.incr_counter("broker.nack")
        ev = u.eval
        # keep the per-job serialization slot held by the nacked eval
        # until it is acked (reference Nack semantics) so a newer eval
        # for the job can't jump ahead of the redelivery; the slot is
        # only freed when the eval is parked for the failed-eval reaper
        if self._deliveries.get(eval_id, 0) >= \
                self._broker.delivery_limit:
            self._release_job_slot_locked(ev, eval_id)
            # too many failed deliveries: park it for the leader reaper
            self._ready.setdefault(FAILED_QUEUE, _Heap()).push(ev)
            self._ready_since[ev.id] = _time.monotonic()
            _tr.event(eval_id, "broker.nack", parked=True,
                      deliveries=self._deliveries.get(eval_id, 0))
            self._broker.notify_ready()
            return None
        # redeliver after a capped jittered exponential delay:
        # linear compounding barely separates a flapping eval from
        # healthy redeliveries, and unjittered delays re-collide a
        # burst of nacked evals at every retry (thundering herd)
        n = max(1, self._deliveries.get(eval_id, 1))
        delay = min(self._broker.max_nack_delay_s,
                    self._broker.initial_nack_delay_s * (2 ** (n - 1)))
        delay *= 0.5 + self._nack_rng.random() / 2.0
        _tr.event(eval_id, "broker.nack", parked=False,
                  deliveries=self._deliveries.get(eval_id, 0),
                  redeliver_delay_s=round(delay, 6))
        deadline = _time.time() + delay
        self._waiting[ev.id] = ev
        heapq.heappush(self._delay_heap, (deadline, ev.id))
        return None

    # ------------------------------------------------------------ plumbing
    def pop_due_delayed(self) -> float:
        """Promote delayed evals whose wait has expired AND fire due
        nack deadlines (called by the broker's single delayed-watcher
        thread).  Returns the seconds until this shard's next deadline
        (or 0.1 when idle).  Nack redelivery is a multi-second safety
        net, so the watcher's 10-100ms cadence is far inside its
        tolerance — and one thread servicing every deadline replaces
        the one-Timer-thread-per-dequeue storm."""
        with self._lock:
            now = _time.time()
            wait = 0.1
            while self._delay_heap and self._delay_heap[0][0] <= now:
                _, eid = heapq.heappop(self._delay_heap)
                ev = self._waiting.pop(eid, None)
                if ev is not None:
                    ev2 = ev
                    if ev2.wait_until:
                        import copy
                        ev2 = copy.copy(ev)
                        ev2.wait_until = 0.0
                    self._enqueue_locked(ev2, ev2.type)
            while self._nack_heap and self._nack_heap[0][0] <= now:
                deadline, eid, token = heapq.heappop(self._nack_heap)
                u = self._unack.get(eid)
                if u is None or u.token != token \
                        or u.nack_deadline != deadline:
                    continue    # stale: acked, paused, or re-armed
                # check and act under ONE lock hold (the RACE903
                # check-then-act class): no window for an ack or an
                # explicit nack to slip between validate and requeue
                self._nack_locked(eid, token)
            if self._delay_heap:
                wait = min(wait, max(0.0, self._delay_heap[0][0] - now))
            if self._nack_heap:
                wait = min(wait, max(0.0, self._nack_heap[0][0] - now))
            return wait

    def flush(self) -> None:
        with self._lock:
            self._nack_heap.clear()
            self._ready.clear()
            self._unack.clear()
            self._job_evals.clear()
            self._blocked.clear()
            self._requeue.clear()
            self._waiting.clear()
            self._delay_heap.clear()
            self._deliveries.clear()
            self._ready_since.clear()

    def ready_count(self) -> int:
        with self._lock:
            return sum(len(h) for h in self._ready.values())

    def oldest_ready_t0(self) -> Optional[float]:
        """Monotonic enqueue time of this shard's oldest ready eval."""
        with self._lock:
            for t0 in self._ready_since.values():
                return t0
            return None

    def outstanding(self, eval_id: str) -> Optional[str]:
        with self._lock:
            u = self._unack.get(eval_id)
            return u.token if u else None

    def snapshot_stats(self) -> dict:
        with self._lock:
            return {
                "ready": {q: len(h) for q, h in self._ready.items()},
                "unacked": len(self._unack),
                "blocked": sum(len(h) for h in self._blocked.values()),
                "waiting": len(self._waiting),
                "dequeues": self._dequeues,
                "nacks": self._nacks,
                "oldest_t0": next(iter(self._ready_since.values()), None),
                "redelivered": {eid: n
                                for eid, n in self._deliveries.items()
                                if n > 1},
            }


class EvalBroker:
    """Facade over S `_Shard` partitions (see module docstring).  All
    public methods keep the pre-shard signatures; `dequeue`/
    `dequeue_batch` additionally accept a `home` shard hint (the
    worker's index) for locality-first stealing."""

    def __init__(self, nack_delay_s: float = DEFAULT_NACK_DELAY_S,
                 initial_nack_delay_s: float = DEFAULT_INITIAL_NACK_DELAY_S,
                 delivery_limit: int = DEFAULT_DELIVERY_LIMIT,
                 max_nack_delay_s: float = DEFAULT_MAX_NACK_DELAY_S,
                 nack_jitter_seed: int = 0xACED,
                 shards: Optional[int] = None):
        # shared ready condition: blocked dequeuers wait here; shards
        # notify through notify_ready().  A generation counter closes
        # the scan-then-wait race (an enqueue landing between a dry
        # scan and the wait bumps the gen, so the waiter re-scans
        # instead of sleeping through the wake-up).
        self._ready_cv = threading.Condition()
        self._ready_gen = 0
        self._enabled = False
        self.nack_delay_s = nack_delay_s
        self.initial_nack_delay_s = initial_nack_delay_s
        self.max_nack_delay_s = max_nack_delay_s
        self.delivery_limit = delivery_limit
        n = shards if shards is not None else _default_shards()
        self.num_shards = max(1, int(n))
        self._shards = [_Shard(self, i, nack_jitter_seed)
                        for i in range(self.num_shards)]
        self._rr = itertools.count()
        self._delay_thread: Optional[threading.Thread] = None
        self._stop_delay = threading.Event()
        # export_metrics rate gate (ISSUE 17 satellite): hot loops pass
        # min_interval_s >= 1 so queue-shape gauges cost one monotonic
        # read per call instead of S lock holds
        self._export_lock = threading.Lock()
        self._last_export = 0.0

    # ------------------------------------------------------------ lifecycle
    def set_enabled(self, enabled: bool) -> None:
        with self._ready_cv:
            prev = self._enabled
            self._enabled = enabled
            if enabled and not prev:
                self._stop_delay.clear()
                self._delay_thread = threading.Thread(
                    target=self._run_delayed_watcher, daemon=True)
                self._delay_thread.start()
        if prev and not enabled:
            self.flush()
        if not enabled:
            self._stop_delay.set()

    @property
    def enabled(self) -> bool:
        with self._ready_cv:    # guarded by _ready_cv: see set_enabled
            return self._enabled

    @property
    def enabled_flag(self) -> bool:
        """Enabled read for the shards' enqueue path.  Nests the shared
        condition inside the calling shard's lock — the one sanctioned
        order (shard lock -> ready condition, same as notify_ready);
        the condition never wraps a shard lock."""
        with self._ready_cv:
            return self._enabled

    def notify_ready(self) -> None:
        """Wake blocked dequeuers (called by shards after making work
        ready; the caller holds only its shard lock — the shared
        condition nests strictly inside shard locks, never around
        them)."""
        with self._ready_cv:
            self._ready_gen += 1
            self._ready_cv.notify_all()

    def ready_count(self) -> int:
        """Evals ready for dequeue right now (not delayed/unacked)."""
        return sum(s.ready_count() for s in self._shards)

    def oldest_ready_age(self) -> float:
        """Seconds the oldest currently-ready eval has been waiting —
        the max across shards (each shard's dict insertion order tracks
        enqueue order, so its first live entry is its oldest)."""
        t0s = [t0 for t0 in (s.oldest_ready_t0() for s in self._shards)
               if t0 is not None]
        if not t0s:
            return 0.0
        return _time.monotonic() - min(t0s)

    def export_metrics(self, min_interval_s: float = 0.0) -> None:
        """Publish queue-shape gauges through the global metrics path
        (surfaced at /v1/metrics next to the worker.dequeue_eval
        counters).  `min_interval_s` rate-gates hot callers: a call
        landing inside the window is a no-op (one monotonic read), so
        per-dequeue loops can't turn the gauge walk into lock traffic —
        the leader's 1s export beat passes the default 0 and always
        publishes."""
        from ..utils.metrics import global_metrics as _m
        if min_interval_s > 0.0:
            now = _time.monotonic()
            with self._export_lock:
                if now - self._last_export < min_interval_s:
                    return
                self._last_export = now
        ready: Dict[str, int] = {}
        unacked = waiting = blocked = 0
        oldest_t0: Optional[float] = None
        redelivered: Dict[str, int] = {}
        for s in self._shards:
            st = s.snapshot_stats()
            for q, cnt in st["ready"].items():
                ready[q] = ready.get(q, 0) + cnt
            unacked += st["unacked"]
            waiting += st["waiting"]
            blocked += st["blocked"]
            if st["oldest_t0"] is not None and \
                    (oldest_t0 is None or st["oldest_t0"] < oldest_t0):
                oldest_t0 = st["oldest_t0"]
            # per-eval delivery counts: only evals past their first
            # delivery (the interesting, bounded set — at most
            # delivery_limit redeliveries each before parking), so
            # gauge cardinality stays proportional to flapping evals,
            # not throughput; the registry's namespace cap absorbs
            # pathological storms as metrics.overflow
            redelivered.update(st["redelivered"])
        oldest = (_time.monotonic() - oldest_t0) if oldest_t0 else 0.0
        _m.set_gauge("broker.ready_count", float(sum(ready.values())))
        _m.set_gauge("broker.redelivering", float(len(redelivered)))
        for eid, cnt in redelivered.items():
            _m.set_gauge(f"broker.deliveries.{eid}", float(cnt))
        _m.set_gauge("broker.oldest_ready_age_s", oldest)
        _m.set_gauge("broker.unacked", float(unacked))
        _m.set_gauge("broker.waiting", float(waiting))
        _m.set_gauge("broker.job_blocked", float(blocked))
        _m.set_gauge("broker.shards", float(self.num_shards))
        for q, cnt in ready.items():
            _m.set_gauge(f"broker.ready.{q}", float(cnt))

    def flush(self) -> None:
        for s in self._shards:
            s.flush()
        self.notify_ready()

    # -------------------------------------------------------------- routing
    def shard_of(self, ev: Evaluation) -> _Shard:
        """A job maps to exactly ONE shard (per-job serialization by
        construction); job-less evals spread by eval id.  crc32, not
        hash(): stable across processes and PYTHONHASHSEED, so replay
        and chaos runs shard identically."""
        if self.num_shards == 1:
            return self._shards[0]
        if ev.job_id:
            key = f"{ev.namespace}\x00{ev.job_id}"
        else:
            key = ev.id
        idx = (zlib.crc32(key.encode("utf-8", "replace")) & 0xFFFFFFFF) \
            % self.num_shards
        return self._shards[idx]

    def _shard_by_token(self, eval_id: str, token: str
                        ) -> Optional[_Shard]:
        """The shard that issued `token` (its index is the token's
        prefix).  Falls back to a scan for foreign token formats."""
        head, _, rest = token.partition(".")
        if rest:
            try:
                idx = int(head)
            except ValueError:
                idx = -1
            if 0 <= idx < self.num_shards:
                return self._shards[idx]
        for s in self._shards:
            if s.outstanding(eval_id) == token:
                return s
        return None

    # ------------------------------------------------------------- enqueue
    def enqueue(self, ev: Evaluation) -> None:
        self.shard_of(ev).enqueue(ev)

    def enqueue_batch(self, evals: List[Evaluation]) -> None:
        """Bulk enqueue, grouped by shard so each shard takes its lock
        once and wakes dequeuers once per group instead of per eval."""
        if self.num_shards == 1:
            self._shards[0].enqueue_batch(evals)
            return
        by_shard: Dict[int, List[Evaluation]] = {}
        for ev in evals:
            by_shard.setdefault(self.shard_of(ev).index, []).append(ev)
        for idx, group in by_shard.items():
            self._shards[idx].enqueue_batch(group)

    def enqueue_all(self, evals: List[Tuple[Evaluation, str]]) -> None:
        """Enqueue (eval, token) pairs; a matching token for an unacked
        eval defers the re-enqueue until that eval is acked.  Routing
        is deterministic by eval content, so the token's unack entry —
        if any — lives in the same shard the eval routes to."""
        by_shard: Dict[int, List[Tuple[Evaluation, str]]] = {}
        for ev, token in evals:
            sh = self.shard_of(ev)
            by_shard.setdefault(sh.index, []).append((ev, token))
        for idx, group in by_shard.items():
            self._shards[idx].enqueue_all(group)

    # ------------------------------------------------------------- dequeue
    def dequeue(self, sched_types: Sequence[str], timeout: float = 0.0,
                home: Optional[int] = None
                ) -> Tuple[Optional[Evaluation], str]:
        """Blocking dequeue: home shard first, then steal round-robin
        across the rest.  `home` defaults to a rotating pick so
        anonymous callers spread load."""
        deadline = _time.monotonic() + timeout
        start = (home if home is not None else next(self._rr)) \
            % self.num_shards
        while True:
            with self._ready_cv:
                gen = self._ready_gen
                enabled = self._enabled
            for k in range(self.num_shards):
                ev, token = self._shards[(start + k) % self.num_shards] \
                    .try_dequeue(sched_types)
                if ev is not None:
                    return ev, token
            remain = deadline - _time.monotonic()
            if remain <= 0 or not enabled:
                return None, ""
            with self._ready_cv:
                if self._ready_gen == gen:
                    self._ready_cv.wait(remain)

    def dequeue_batch(self, sched_types: Sequence[str], max_batch: int,
                      timeout: float = 0.0, home: Optional[int] = None
                      ) -> List[Tuple[Evaluation, str]]:
        """Drain up to max_batch ready evals (the TPU coalescing point).
        Blocks for the first eval only; the rest are taken
        opportunistically — home shard first, stealing across the other
        shards when it runs dry so no shard strands work."""
        first, token = self.dequeue(sched_types, timeout, home=home)
        if first is None:
            return []
        out = [(first, token)]
        start = (home if home is not None else 0) % self.num_shards
        for k in range(self.num_shards):
            if len(out) >= max_batch:
                break
            shard = self._shards[(start + k) % self.num_shards]
            out.extend(shard.try_dequeue_n(sched_types,
                                           max_batch - len(out)))
        # dequeue-batch size histogram (p50/p99 via the metrics
        # reservoir) — the observability face of the BatchController
        from ..utils.metrics import global_metrics as _m
        _m.add_sample("broker.dequeue_batch_size", float(len(out)))
        return out

    # --------------------------------------------------------- nack timers
    def pause_nack_timeout(self, eval_id: str, token: str) -> Optional[str]:
        """Stop the redelivery timer while the holder does long work
        (reference: eval_broker PauseNackTimeout, used while waiting on
        raft / the fused solve). The holder must still ack or nack."""
        sh = self._shard_by_token(eval_id, token)
        if sh is None:
            return "token mismatch"
        return sh.pause_nack_timeout(eval_id, token)

    def resume_nack_timeout(self, eval_id: str,
                            token: str) -> Optional[str]:
        sh = self._shard_by_token(eval_id, token)
        if sh is None:
            return "token mismatch"
        return sh.resume_nack_timeout(eval_id, token)

    def pause_nack_batch(self, pairs: Sequence[Tuple[str, str]]
                         ) -> List[Optional[str]]:
        """Pause redelivery for many (eval_id, token) pairs with one
        lock hold per touched shard (the fused-batch hot path)."""
        return self._batch_by_shard(pairs, "pause_nack_batch")

    # ------------------------------------------------------------ ack/nack
    def ack(self, eval_id: str, token: str) -> Optional[str]:
        sh = self._shard_by_token(eval_id, token)
        if sh is None:
            return "token mismatch"
        return sh.ack(eval_id, token)

    def ack_batch(self, pairs: Sequence[Tuple[str, str]]
                  ) -> List[Optional[str]]:
        """Ack many (eval_id, token) pairs with one lock hold per
        touched shard; per-pair errors aligned with the input."""
        return self._batch_by_shard(pairs, "ack_batch")

    def _batch_by_shard(self, pairs: Sequence[Tuple[str, str]],
                        method: str) -> List[Optional[str]]:
        """Group (eval_id, token) pairs by issuing shard and apply the
        shard's batch method once per group, preserving input order in
        the returned error list."""
        out: List[Optional[str]] = [None] * len(pairs)
        by_shard: Dict[int, List[Tuple[int, str, str]]] = {}
        for i, (eid, tok) in enumerate(pairs):
            sh = self._shard_by_token(eid, tok)
            if sh is None:
                out[i] = "token mismatch"
                continue
            by_shard.setdefault(sh.index, []).append((i, eid, tok))
        for idx, group in by_shard.items():
            errs = getattr(self._shards[idx], method)(
                [(eid, tok) for _i, eid, tok in group])
            for (i, _eid, _tok), err in zip(group, errs):
                out[i] = err
        return out

    def nack(self, eval_id: str, token: str) -> Optional[str]:
        sh = self._shard_by_token(eval_id, token)
        if sh is None:
            return "token mismatch"
        return sh.nack(eval_id, token)

    # ------------------------------------------------------ delayed watcher
    def _run_delayed_watcher(self) -> None:
        while not self._stop_delay.is_set():
            wait = 0.1
            for s in self._shards:
                wait = min(wait, s.pop_due_delayed())
            self._stop_delay.wait(max(wait, 0.01))

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        shard_stats = [s.snapshot_stats() for s in self._shards]
        by_sched: Dict[str, int] = {}
        for st in shard_stats:
            for q, cnt in st["ready"].items():
                by_sched[q] = by_sched.get(q, 0) + cnt
        t0s = [st["oldest_t0"] for st in shard_stats
               if st["oldest_t0"] is not None]
        oldest = (_time.monotonic() - min(t0s)) if t0s else 0.0
        return {
            "total_ready": sum(by_sched.values()),
            "total_unacked": sum(st["unacked"] for st in shard_stats),
            "total_blocked": sum(st["blocked"] for st in shard_stats),
            "total_waiting": sum(st["waiting"] for st in shard_stats),
            "by_scheduler": by_sched,
            "dequeues": sum(st["dequeues"] for st in shard_stats),
            "nacks": sum(st["nacks"] for st in shard_stats),
            "oldest_ready_age_s": round(oldest, 6),
            "shards": self.num_shards,
            "ready_by_shard": [sum(st["ready"].values())
                               for st in shard_stats],
        }

    def outstanding(self, eval_id: str) -> Optional[str]:
        for s in self._shards:
            token = s.outstanding(eval_id)
            if token is not None:
                return token
        return None
