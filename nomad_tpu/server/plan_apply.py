"""The plan applier — the single serialization point of the control plane.

Workers plan optimistically against snapshots; this component re-validates
every plan against the LATEST state before commit, dropping per-node
placements that no longer fit, and hands partial committers a refresh
index so they retry against fresh data.

Reference: nomad/plan_apply.go — planApply loop :71-178, evaluatePlan
:399, evaluatePlanPlacements :436 (per-node fit re-check with partial
commit + RefreshIndex :568-584), evaluateNodePlan :628, applyPlan :204.
The reference fans per-node checks over an EvaluatePool of NumCPU/2
goroutines; here a single pass suffices because the fit check itself is
vector math (structs.funcs.allocs_fit), and the TPU batch already did
the heavy scoring.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..structs import (ALLOC_DESIRED_STOP, EVAL_STATUS_BLOCKED,
                       EVAL_TRIGGER_PREEMPTION, Allocation, Evaluation, Plan,
                       PlanResult)
from ..structs.funcs import allocs_fit
from .plan_queue import PendingPlan, PlanQueue

# applier callback: (plan, result) -> commit index. In the single-server
# build this writes the state store directly; under raft it is the
# ApplyPlanResults log entry.
ApplyFn = Callable[[Plan, PlanResult], int]


def evaluate_node_plan(snapshot, plan: Plan, node_id: str
                       ) -> Tuple[bool, str]:
    """Can this node accommodate the plan's allocations for it?
    (reference: plan_apply.go:628)."""
    new_allocs = plan.node_allocation.get(node_id, [])
    if not new_allocs:
        return True, ""
    node = snapshot.node_by_id(node_id)
    if node is None:
        return False, "node does not exist"
    if node.terminal_status():
        return False, "node is not ready for placements"
    if node.drain or not node.ready():
        return False, "node is not eligible"

    existing = [a for a in snapshot.allocs_by_node(node_id)
                if not a.terminal_status()]
    remove_ids = {a.id for a in plan.node_update.get(node_id, [])}
    remove_ids.update(a.id for a in plan.node_preemptions.get(node_id, []))
    proposed = [a for a in existing if a.id not in remove_ids]
    # an update of an existing alloc replaces it
    new_ids = {a.id for a in new_allocs}
    proposed = [a for a in proposed if a.id not in new_ids]
    proposed.extend(new_allocs)

    fit, reason, _used = allocs_fit(node, proposed, check_devices=True)
    if not fit:
        return False, reason or "does not fit"
    return True, ""


def evaluate_plan(snapshot, plan: Plan) -> PlanResult:
    """Re-check the whole plan against `snapshot`, keeping only nodes that
    still fit; partial results carry a refresh index."""
    # stops always commit; placements and the preemptions that make room
    # for them are gated per node on the fit re-check
    result = PlanResult(
        node_update=dict(plan.node_update),
        deployment=plan.deployment,
        deployment_updates=list(plan.deployment_updates))

    if plan.all_at_once:
        # all-or-nothing: any failing node voids every placement
        for node_id in plan.node_allocation:
            ok, _why = evaluate_node_plan(snapshot, plan, node_id)
            if not ok:
                result.node_allocation = {}
                result.deployment = None
                result.deployment_updates = []
                result.refresh_index = snapshot.latest_index() \
                    if hasattr(snapshot, "latest_index") else snapshot.index
                return result
        result.node_allocation = dict(plan.node_allocation)
        result.node_preemptions = dict(plan.node_preemptions)
        return result

    partial = False
    for node_id in plan.node_allocation:
        ok, _why = evaluate_node_plan(snapshot, plan, node_id)
        if ok:
            result.node_allocation[node_id] = plan.node_allocation[node_id]
            if node_id in plan.node_preemptions:
                result.node_preemptions[node_id] = \
                    plan.node_preemptions[node_id]
        else:
            partial = True
    if partial:
        result.refresh_index = max(snapshot.table_index("nodes"),
                                   snapshot.table_index("allocs"))
        # a partial commit voids the deployment objects — the scheduler
        # recreates them on retry (reference: plan_apply.go:560-566)
        result.deployment = None
        result.deployment_updates = []
    return result


class PlanApplier:
    """Owns the applier loop: dequeue pending plan -> evaluate -> apply."""

    def __init__(self, queue: PlanQueue, store, apply_fn: ApplyFn,
                 create_evals: Optional[Callable[[List[Evaluation]], None]]
                 = None):
        self.queue = queue
        self.store = store
        self.apply_fn = apply_fn
        self.create_evals = create_evals
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            pending = self.queue.dequeue(0.2)
            if pending is None:
                continue
            try:
                self.apply_one(pending)
            except Exception as e:   # keep the applier alive
                pending.future.respond(None, f"plan apply error: {e}")

    def apply_one(self, pending: PendingPlan) -> None:
        from ..utils.metrics import global_metrics as _m
        plan = pending.plan
        _m.set_gauge("plan.queue_depth", self.queue.depth()
                     if hasattr(self.queue, "depth") else 0)
        snapshot = self.store.snapshot()
        with _m.timed("plan.evaluate"):
            result = evaluate_plan(snapshot, plan)
        if result.is_no_op() and not result.refresh_index:
            pending.future.respond(result, None)
            return
        with _m.timed("plan.apply"):
            index = self.apply_fn(plan, result)
        result.alloc_index = index
        if result.refresh_index:
            _m.incr_counter("plan.partial_commit")
        _m.incr_counter("plan.node_allocations",
                        sum(len(v) for v in result.node_allocation.values()))

        # preempted allocs need follow-up evals for their jobs
        if self.create_evals and plan.node_preemptions:
            preempted_jobs = {}
            for allocs in plan.node_preemptions.values():
                for a in allocs:
                    preempted_jobs[(a.namespace, a.job_id)] = a
            evals = []
            for (ns, job_id), a in preempted_jobs.items():
                evals.append(Evaluation(
                    namespace=ns, job_id=job_id,
                    type=a.job.type if a.job else "service",
                    priority=a.job.priority if a.job else 50,
                    triggered_by=EVAL_TRIGGER_PREEMPTION))
            self.create_evals(evals)
        pending.future.respond(result, None)
