"""The plan applier — the single serialization point of the control plane.

Workers plan optimistically against snapshots; this component re-validates
every plan against the LATEST state before commit, dropping per-node
placements that no longer fit, and hands partial committers a refresh
index so they retry against fresh data.

Reference: nomad/plan_apply.go — planApply loop :71-178, evaluatePlan
:399, evaluatePlanPlacements :436 (per-node fit re-check with partial
commit + RefreshIndex :568-584), evaluateNodePlan :628, applyPlan :204,
plan_apply_pool.go (per-node verify fan-out over NumCPU/2 workers).

PIPELINING: plan N's raft consensus round trip overlaps plan N+1's
evaluation — the applier evaluates N+1 against plan N's KNOWN result
overlaid on the snapshot (`_OverlaySnapshot`), dispatches N+1's raft
apply, and only then waits/responds for N (the reference overlaps the
same region via applyPlan's async raft future + asyncPlanWait; it
re-snapshots at min-index instead of overlaying, trading the extra
wait for a narrower optimism window — both designs accept the same
hazard class, writes landing between evaluate and apply).  A plan is
only held outstanding while another is ALREADY queued, so a singleton
plan keeps today's latency.
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from ..structs import (ALLOC_DESIRED_STOP, EVAL_STATUS_BLOCKED,
                       EVAL_TRIGGER_PREEMPTION, Allocation, Evaluation, Plan,
                       PlanResult)
from ..structs.funcs import allocs_fit
from .plan_queue import PendingPlan, PlanQueue

# applier callback: (plan, result) -> commit index. In the single-server
# build this writes the state store directly; under raft it is the
# ApplyPlanResults log entry.
ApplyFn = Callable[[Plan, PlanResult], int]


def evaluate_node_plan(snapshot, plan: Plan, node_id: str
                       ) -> Tuple[bool, str]:
    """Can this node accommodate the plan's allocations for it?
    (reference: plan_apply.go:628)."""
    new_allocs = plan.node_allocation.get(node_id, [])
    if not new_allocs:
        return True, ""
    node = snapshot.node_by_id(node_id)
    if node is None:
        return False, "node does not exist"
    if node.terminal_status():
        return False, "node is not ready for placements"
    if node.drain or not node.ready():
        return False, "node is not eligible"

    existing = [a for a in snapshot.allocs_by_node(node_id)
                if not a.terminal_status()]
    remove_ids = {a.id for a in plan.node_update.get(node_id, [])}
    remove_ids.update(a.id for a in plan.node_preemptions.get(node_id, []))
    proposed = [a for a in existing if a.id not in remove_ids]
    # an update of an existing alloc replaces it
    new_ids = {a.id for a in new_allocs}
    proposed = [a for a in proposed if a.id not in new_ids]
    proposed.extend(new_allocs)

    fit, reason, _used = allocs_fit(node, proposed, check_devices=True)
    if not fit:
        return False, reason or "does not fit"
    return True, ""


class _OverlaySnapshot:
    """A snapshot with an in-flight plan's result applied on top: the
    applier KNOWS what plan N will commit, so plan N+1 validates
    against base+N without waiting for the raft apply (reference
    analog: plan_apply.go's "snapshot at min-index" — ours trades that
    wait for an optimistic overlay)."""

    def __init__(self, base, result: PlanResult):
        self._base = base
        self._extra: Dict[str, List[Allocation]] = {
            nid: list(allocs)
            for nid, allocs in result.node_allocation.items()}
        removed = set()
        for allocs in result.node_update.values():
            removed.update(a.id for a in allocs)
        for allocs in result.node_preemptions.values():
            removed.update(a.id for a in allocs)
        self._removed = removed

    def allocs_by_node(self, node_id: str):
        # idempotent whether or not the overlaid plan has ALREADY been
        # applied to the base (the base is a fresh snapshot racing the
        # consensus thread): stops/preemptions filter by id, placements
        # replace any same-id alloc the base may carry
        extra = self._extra.get(node_id, ())
        extra_ids = {a.id for a in extra}
        base = [a for a in self._base.allocs_by_node(node_id)
                if a.id not in self._removed and a.id not in extra_ids]
        return base + list(extra)

    def __getattr__(self, name):
        return getattr(self._base, name)


#: per-node verify fan-out (reference: plan_apply_pool.go NumCPU/2
#: workers); small plans stay on the applier thread
_POOL_MIN_NODES = 16
_pool: Optional[ThreadPoolExecutor] = None
_pool_lock = threading.Lock()


def _verify_pool() -> ThreadPoolExecutor:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=max(2, (os.cpu_count() or 4) // 2),
                thread_name_prefix="plan-verify")
        return _pool


def evaluate_plan(snapshot, plan: Plan) -> PlanResult:
    """Re-check the whole plan against `snapshot`, keeping only nodes that
    still fit; partial results carry a refresh index."""
    # stops always commit; placements and the preemptions that make room
    # for them are gated per node on the fit re-check
    result = PlanResult(
        node_update=dict(plan.node_update),
        deployment=plan.deployment,
        deployment_updates=list(plan.deployment_updates))

    if plan.all_at_once:
        # all-or-nothing: any failing node voids every placement
        for node_id in plan.node_allocation:
            ok, _why = evaluate_node_plan(snapshot, plan, node_id)
            if not ok:
                result.node_allocation = {}
                result.deployment = None
                result.deployment_updates = []
                result.refresh_index = snapshot.latest_index() \
                    if hasattr(snapshot, "latest_index") else snapshot.index
                return result
        result.node_allocation = dict(plan.node_allocation)
        result.node_preemptions = dict(plan.node_preemptions)
        return result

    partial = False
    node_ids = list(plan.node_allocation)
    if len(node_ids) >= _POOL_MIN_NODES:
        oks = list(_verify_pool().map(
            lambda nid: evaluate_node_plan(snapshot, plan, nid)[0],
            node_ids))
    else:
        oks = [evaluate_node_plan(snapshot, plan, nid)[0]
               for nid in node_ids]
    for node_id, ok in zip(node_ids, oks):
        if ok:
            result.node_allocation[node_id] = plan.node_allocation[node_id]
            if node_id in plan.node_preemptions:
                result.node_preemptions[node_id] = \
                    plan.node_preemptions[node_id]
        else:
            partial = True
    if partial:
        result.refresh_index = max(snapshot.table_index("nodes"),
                                   snapshot.table_index("allocs"))
        # a partial commit voids the deployment objects — the scheduler
        # recreates them on retry (reference: plan_apply.go:560-566)
        result.deployment = None
        result.deployment_updates = []
    return result


class _Outstanding:
    """A dispatched-but-unacknowledged apply: one plan, or a
    group-commit batch of K plans riding a single raft entry (one
    fsync); each member keeps its own future + result."""
    __slots__ = ("items", "finish")

    def __init__(self, items, finish):
        self.items = items            # [(pending, plan, result), ...]
        self.finish = finish          # blocks until raft-applied


class PlanApplier:
    """Owns the applier loop: dequeue pending plan -> evaluate ->
    apply, pipelined when plans are queued back to back (see module
    docstring)."""

    def __init__(self, queue: PlanQueue, store, apply_fn: ApplyFn,
                 create_evals: Optional[Callable[[List[Evaluation]], None]]
                 = None, apply_async_fn=None, apply_batch_async_fn=None,
                 group_commit: int = 1):
        self.queue = queue
        self.store = store
        self.apply_fn = apply_fn
        self.apply_async_fn = apply_async_fn
        #: group commit (ISSUE 17): batch fn takes [(plan, result)] and
        #: dispatches ONE raft entry carrying all K results; group_commit
        #: caps K.  Plans are only grouped when already queued back to
        #: back, so a singleton keeps the unbatched latency.
        self.apply_batch_async_fn = apply_batch_async_fn
        self.group_commit = max(1, int(group_commit))
        self.create_evals = create_evals
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        out: Optional[_Outstanding] = None
        while not self._stop.is_set():
            # only hold a plan outstanding while another is already
            # queued: a singleton plan is finalized immediately and
            # keeps the unpipelined latency
            pending = self.queue.dequeue(0.0 if out is not None else 0.2)
            if pending is None:
                if out is not None:
                    out = self._finalize(out)
                continue
            # clear the outstanding slot BEFORE the raising path:
            # apply_one owns `prev` from here (it finalizes it on every
            # branch, and _finalize never raises), so an exception out
            # of apply_one can no longer leave a consumed _Outstanding
            # in the loop slot to be finalized — and its future
            # responded — a second time
            prev, out = out, None
            try:
                out = self.apply_one(pending, prev)
            except Exception as e:   # keep the applier alive
                pending.future.respond(None, f"plan apply error: {e}")
        if out is not None:
            self._finalize(out)

    def apply_one(self, pending: PendingPlan,
                  out: Optional[_Outstanding] = None
                  ) -> Optional[_Outstanding]:
        try:
            return self._apply_one(pending, out)
        except Exception:
            # the handed-over outstanding plan must reach its finalize
            # exactly once even when THIS plan's evaluate/dispatch blows
            # up — _finalize error-responds internally and never raises
            if out is not None:
                self._finalize(out)
            raise

    def _apply_one(self, pending: PendingPlan,
                   out: Optional[_Outstanding]
                   ) -> Optional[_Outstanding]:
        from ..utils.metrics import global_metrics as _m
        _m.set_gauge("plan.queue_depth", self.queue.depth()
                     if hasattr(self.queue, "depth") else 0)
        # group commit: opportunistically drain up to K-1 more queued
        # plans into this round — never waits, so an idle queue keeps
        # the per-plan latency and a saturated one amortizes the fsync
        group = [pending]
        if self.apply_batch_async_fn is not None and self.group_commit > 1:
            while len(group) < self.group_commit:
                extra = self.queue.dequeue(0.0)
                if extra is None:
                    break
                group.append(extra)
        snapshot = self.store.snapshot()
        if out is not None:
            # evaluate against base + the in-flight plans' known results
            # (the overlay is idempotent if the apply already landed)
            for _p, _pl, res in out.items:
                snapshot = _OverlaySnapshot(snapshot, res)
        items = []
        for p in group:
            try:
                with _m.timed("plan.evaluate"):
                    result = evaluate_plan(snapshot, p.plan)
            except Exception as e:
                # a poisoned group member must not strand the others
                p.future.respond(None, f"plan apply error: {e}")
                continue
            if result.is_no_op() and not result.refresh_index:
                p.future.respond(result, None)
                continue
            items.append((p, p.plan, result))
            # later members validate against earlier members' results:
            # intra-batch conflicts surface as partial commits exactly
            # as they would pipelined one by one
            snapshot = _OverlaySnapshot(snapshot, result)
        if not items:
            return out
        if len(items) > 1 and self.apply_batch_async_fn is not None:
            try:
                index, finish = self.apply_batch_async_fn(
                    [(pl, res) for _p, pl, res in items])
            except Exception as e:
                for p, _pl, _res in items:
                    p.future.respond(None, f"plan apply error: {e}")
                return out
            _m.incr_counter("plan.group_commits")
            _m.incr_counter("plan.raft_applies")
            _m.add_sample("plan.group_commit_size", float(len(items)))
            new_out = _Outstanding(items, finish)
            if out is not None:
                # the batch's consensus is in flight: the previous
                # round's wait+respond rides under it
                self._finalize(out)
            return new_out
        if self.apply_async_fn is not None and len(items) == 1:
            p, plan, result = items[0]
            index, finish = self.apply_async_fn(plan, result)
            _m.incr_counter("plan.raft_applies")
            new_out = _Outstanding(items, finish)
            if out is not None:
                # plan N+1's consensus is in flight: N's wait+respond
                # rides under it
                self._finalize(out)
            return new_out
        # legacy synchronous path (no async apply wired)
        if out is not None:
            self._finalize(out)
        for p, plan, result in items:
            with _m.timed("plan.apply"):
                index = self.apply_fn(plan, result)
            result.alloc_index = index
            self._account_and_respond(p, plan, result)
        return None

    def _finalize(self, out: _Outstanding):
        """Wait out a dispatched apply and respond every member future —
        exactly once, never raising: every failure path error-responds
        instead (PlanFuture.respond is first-wins, so a partial
        _account_and_respond that already delivered the result cannot
        be overwritten by the trailing error)."""
        from ..utils.metrics import global_metrics as _m
        try:
            with _m.timed("plan.apply"):
                index = out.finish(10.0)
        except Exception as e:
            for pending, _plan, _result in out.items:
                pending.future.respond(None, f"plan apply error: {e}")
            return None
        for pending, plan, result in out.items:
            result.alloc_index = index
            try:
                self._account_and_respond(pending, plan, result)
            except Exception as e:
                pending.future.respond(None, f"plan apply error: {e}")
        return None

    def _account_and_respond(self, pending, plan: Plan,
                             result: PlanResult) -> None:
        from ..utils.metrics import global_metrics as _m
        from ..utils.tracing import global_tracer as _tr
        if result.refresh_index:
            _m.incr_counter("plan.partial_commit")
        _m.incr_counter("plan.node_allocations",
                        sum(len(v) for v in result.node_allocation.values()))
        _tr.event(plan.eval_id, "plan.apply",
                  n_alloc=sum(len(v)
                              for v in result.node_allocation.values()),
                  n_stop=sum(len(v) for v in result.node_update.values()),
                  n_preempt=sum(len(v)
                                for v in result.node_preemptions.values()),
                  partial=bool(result.refresh_index),
                  alloc_index=result.alloc_index)
        # preempted allocs need follow-up evals for their jobs
        if self.create_evals and plan.node_preemptions:
            preempted_jobs = {}
            for allocs in plan.node_preemptions.values():
                for a in allocs:
                    preempted_jobs[(a.namespace, a.job_id)] = a
            evals = []
            for (ns, job_id), a in preempted_jobs.items():
                evals.append(Evaluation(
                    namespace=ns, job_id=job_id,
                    type=a.job.type if a.job else "service",
                    priority=a.job.priority if a.job else 50,
                    triggered_by=EVAL_TRIGGER_PREEMPTION))
            self.create_evals(evals)
        pending.future.respond(result, None)
