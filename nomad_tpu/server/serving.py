"""Serving tier: admission control + adaptive micro-batching (ISSUE 6).

Sits between the eval broker and the solver dispatch.  Everything bench
measured through PR 5 was closed-loop — fixed batches, wait for the
answer; production traffic is open-loop job churn, where a fixed
`batch_size` dequeue either starves the device (tiny batches pay the
per-dispatch overhead over and over) or blows the tail (deep backlogs
capped at 8 evals per solve).  Three cooperating pieces:

  EwmaSolveModel     EWMA solve-time model per batch-size bucket, fed
                     by the worker after every solve (and by
                     ResidentSolver.last_solve_stats on the bench
                     serving path).
  BatchController    sizes each dequeue_batch from queue depth, the
                     oldest ready eval's age, and the model: close the
                     batch early when age + predicted solve time
                     approaches the SLO budget, grow toward max_batch
                     when the backlog is deep.
  AdmissionController bounded broker ingress with priority-aware
                     shedding (shed evals land in BlockedEvals.shed —
                     never dropped, readmitted on drain), per-namespace
                     token-bucket fairness, and brownout mode (degrade
                     the solve wave budget under sustained overload,
                     restore on drain).

All controller state is shared across worker threads and the leader's
eval-ingress path, so every class here owns its lock and keeps writes
under it (nomadlint LOCK301 covers helpers reached by composition from
threaded classes).
"""
from __future__ import annotations

import os
import threading
import time as _time
from typing import Dict, List, Optional, Tuple

from ..structs import JOB_TYPE_CORE, Evaluation

#: default SLO budget for an eval's queue-age + solve time (50ms: the
#: open-loop bench's p99 acceptance bar)
DEFAULT_SLO_BUDGET_S = 0.05
#: adaptive ceiling — how far the controller may grow a micro-batch
DEFAULT_MAX_BATCH = 64
#: evals at or above this priority ride the bypass lane: dequeued work
#: is solved singly ahead of the fused bulk batch, and admission never
#: sheds them (interactive / operator-driven evals)
DEFAULT_BYPASS_PRIORITY = 80
#: bounded broker ingress (ready + waiting evals) before shedding
DEFAULT_MAX_PENDING = 4096
#: per-namespace token-bucket refill rate / burst (fairness is only
#: enforced above the fairness watermark — work-conserving under light
#: load, so a lone tenant may use the whole queue)
DEFAULT_NS_RATE = 512.0
DEFAULT_NS_BURST = 1024.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


class EwmaSolveModel:
    """EWMA of observed solve wall time per batch-size bucket.

    Buckets are pow2 (1, 2, 4, ... max): solve cost is dominated by the
    per-dispatch overhead plus a per-eval marginal term, both smooth in
    log-batch-size, so a handful of buckets with linear interpolation
    between them predicts well after a few dozen observations.
    """

    def __init__(self, alpha: float = 0.25,
                 default_fixed_s: float = 0.004,
                 default_per_eval_s: float = 0.0005):
        self._lock = threading.Lock()
        self._ewma: Dict[int, float] = {}     # bucket pow2 -> seconds
        self.alpha = alpha
        self.default_fixed_s = default_fixed_s
        self.default_per_eval_s = default_per_eval_s
        self._observations = 0

    @staticmethod
    def _bucket(n: int) -> int:
        return 1 << max(0, (max(n, 1) - 1).bit_length())

    def observe(self, n_evals: int, wall_s: float) -> None:
        if n_evals <= 0 or wall_s <= 0:
            return
        b = self._bucket(n_evals)
        with self._lock:
            prev = self._ewma.get(b)
            self._ewma[b] = (wall_s if prev is None
                             else prev + self.alpha * (wall_s - prev))
            self._observations += 1

    def predict(self, n_evals: int) -> float:
        """Predicted wall seconds to solve a batch of `n_evals`."""
        n = max(n_evals, 1)
        b = self._bucket(n)
        with self._lock:
            if not self._ewma:
                return self.default_fixed_s + n * self.default_per_eval_s
            v = self._ewma.get(b)
            if v is not None:
                return v
            # nearest observed buckets below/above, linear in n between
            lo = max((k for k in self._ewma if k < b), default=None)
            hi = min((k for k in self._ewma if k > b), default=None)
            if lo is not None and hi is not None:
                flo, fhi = self._ewma[lo], self._ewma[hi]
                t = (n - lo) / max(hi - lo, 1)
                return flo + t * (fhi - flo)
            if lo is not None:
                # extrapolate with the default marginal slope
                return self._ewma[lo] + (n - lo) * self.default_per_eval_s
            return max(self._ewma[hi]          # smaller than anything seen
                       - (hi - n) * self.default_per_eval_s, 1e-5)

    def observations(self) -> int:
        with self._lock:
            return self._observations

    def snapshot(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._ewma)


class BatchController:
    """Size the next dequeue_batch under the SLO budget.

    Close rule: pick the largest candidate batch size n (pow2 up to
    max_batch) such that the oldest ready eval's age plus the model's
    predicted solve time for n stays inside `slo_budget_s * margin`.
    The margin absorbs model error and the dequeue/ack overhead the
    model doesn't see.  When nothing fits — the oldest eval has already
    blown the budget — the controller flips to DRAIN mode and returns
    max_batch: the late eval is late under any decision, and maximum
    evals/s clears the backlog (and restores the SLO) soonest.  Deep
    backlogs grow the batch naturally: queue depth caps the candidate
    from below, the SLO budget from above.
    """

    def __init__(self, model: EwmaSolveModel,
                 slo_budget_s: float = DEFAULT_SLO_BUDGET_S,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 min_batch: int = 1, margin: float = 0.6):
        self._lock = threading.Lock()
        self.model = model
        self.slo_budget_s = slo_budget_s
        self.max_batch = max(int(max_batch), 1)
        self.min_batch = max(int(min_batch), 1)
        self.margin = margin
        self._last_target = self.min_batch

    def target_batch(self, ready: int, oldest_age_s: float) -> int:
        """Batch size for the next dequeue given queue state."""
        budget = self.slo_budget_s * self.margin - max(oldest_age_s, 0.0)
        best = None
        n = self.min_batch
        while n <= self.max_batch:
            if self.model.predict(n) <= budget:
                best = n
            n <<= 1
        if best is None:
            best = self.max_batch      # drain mode (see class note)
        # no point sizing past the backlog: dequeue_batch is
        # opportunistic, but a tight target keeps the controller's
        # decisions (and the recorded histogram) honest
        best = max(self.min_batch, min(best, max(ready, 1)))
        with self._lock:
            self._last_target = best
        return best

    def last_target(self) -> int:
        with self._lock:
            return self._last_target


class TokenBucket:
    """Classic token bucket; take() under the owner's call-site lock is
    fine, but the bucket carries its own lock so direct use is safe."""

    def __init__(self, rate: float, burst: float):
        self._lock = threading.Lock()
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._stamp = _time.monotonic()

    def take(self, n: float = 1.0) -> bool:
        now = _time.monotonic()
        with self._lock:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._stamp)
                               * self.rate)
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def level(self) -> float:
        with self._lock:
            return self._tokens


class AdmissionController:
    """Bounded ingress + fairness + brownout for the eval broker.

    `offer` decides admit/shed for one arriving eval given the broker's
    current ready count; shed evals are the CALLER's responsibility to
    park in BlockedEvals.shed (never dropped).  `readmit_quota` hands
    drain capacity back: when the queue falls under the low watermark
    the caller pops that many shed evals back into the broker.
    Brownout trips after the queue has been above the high watermark
    for `brownout_after_s` straight, and restores on drain; while
    active, workers degrade the solve (reduced wave budget — leftovers
    follow the normal retry path) and the protect threshold is the only
    admission lane.
    """

    def __init__(self, max_pending: int = DEFAULT_MAX_PENDING,
                 protect_priority: int = DEFAULT_BYPASS_PRIORITY,
                 ns_rate: float = DEFAULT_NS_RATE,
                 ns_burst: float = DEFAULT_NS_BURST,
                 fairness_watermark: float = 0.5,
                 brownout_high: float = 0.75,
                 brownout_low: float = 0.25,
                 brownout_after_s: float = 1.0):
        self._lock = threading.Lock()
        self.max_pending = max(int(max_pending), 1)
        self.protect_priority = int(protect_priority)
        self.ns_rate = float(ns_rate)
        self.ns_burst = float(ns_burst)
        self.fairness_watermark = fairness_watermark
        self.brownout_high = brownout_high
        self.brownout_low = brownout_low
        self.brownout_after_s = brownout_after_s
        self._buckets: Dict[str, TokenBucket] = {}
        self._brownout = False
        self._over_since: Optional[float] = None
        self._offered = 0
        self._admitted = 0
        self._shed = 0
        self._shed_by_ns: Dict[str, int] = {}
        self._brownouts = 0

    # ------------------------------------------------------------ ingress
    def offer(self, ev: Evaluation, ready_count: int) -> bool:
        """True = admit (caller enqueues), False = shed (caller parks
        the eval in BlockedEvals.shed)."""
        return self.offer_ex(ev, ready_count)[0]

    def offer_ex(self, ev: Evaluation, ready_count: int
                 ) -> "Tuple[bool, str]":
        """`offer` plus the shed cause — "max_pending", "brownout" or
        "fairness" when shedding, "" when admitted.  The cause lands on
        the eval's admit trace span (shed causality, ISSUE 10)."""
        now = _time.monotonic()
        protected = (ev.priority >= self.protect_priority
                     or ev.type == JOB_TYPE_CORE)
        with self._lock:
            # every offer is either admitted or shed — the invariant
            # harness checks offered == admitted + shed holds exactly
            self._offered += 1
            self._track_overload_locked(ready_count, now)
            if protected:
                self._admitted += 1
                return True, ""
            if ready_count >= self.max_pending:
                self._shed_locked(ev)
                return False, "max_pending"
            if self._brownout:
                self._shed_locked(ev)
                return False, "brownout"
            if ready_count >= self.fairness_watermark * self.max_pending:
                b = self._buckets.get(ev.namespace)
                if b is None:
                    b = TokenBucket(self.ns_rate, self.ns_burst)
                    self._buckets[ev.namespace] = b
                if not b.take():
                    self._shed_locked(ev)
                    return False, "fairness"
            self._admitted += 1
            return True, ""

    def _shed_locked(self, ev: Evaluation) -> None:
        self._shed += 1
        self._shed_by_ns[ev.namespace] = \
            self._shed_by_ns.get(ev.namespace, 0) + 1

    def _track_overload_locked(self, ready_count: int, now: float) -> None:
        if ready_count >= self.brownout_high * self.max_pending:
            if self._over_since is None:
                self._over_since = now
            elif (not self._brownout
                  and now - self._over_since >= self.brownout_after_s):
                self._brownout = True
                self._brownouts += 1
        else:
            self._over_since = None

    # -------------------------------------------------------------- drain
    def readmit_quota(self, ready_count: int, batch: int = 0) -> int:
        """How many shed evals the caller may pop back into the broker
        right now.  Non-zero only under the low watermark; also clears
        brownout there (restore on drain)."""
        with self._lock:
            self._track_overload_locked(ready_count, _time.monotonic())
            if ready_count > self.brownout_low * self.max_pending:
                return 0
            if self._brownout:
                self._brownout = False
            room = self.max_pending - ready_count
            return max(0, min(room, batch or DEFAULT_MAX_BATCH))

    def brownout_active(self) -> bool:
        with self._lock:
            return self._brownout

    def stats(self) -> dict:
        with self._lock:
            return {
                "offered": self._offered,
                "admitted": self._admitted,
                "shed": self._shed,
                "shed_by_namespace": dict(self._shed_by_ns),
                "brownout": self._brownout,
                "brownouts_entered": self._brownouts,
            }


class ServingTier:
    """Bundle of the serving-tier controllers plus their knobs, hung off
    the Server and shared by every worker.  `overrides` (agent config
    `server { serving { ... } }` stanza) win over env vars win over
    defaults."""

    #: knob -> (env var, type, default)
    KNOBS = {
        "slo_budget_s": ("NOMAD_TPU_SLO_BUDGET_S", float,
                         DEFAULT_SLO_BUDGET_S),
        "max_batch": ("NOMAD_TPU_MAX_BATCH", int, DEFAULT_MAX_BATCH),
        "bypass_priority": ("NOMAD_TPU_BYPASS_PRIORITY", int,
                            DEFAULT_BYPASS_PRIORITY),
        "max_pending": ("NOMAD_TPU_ADMIT_MAX_PENDING", int,
                        DEFAULT_MAX_PENDING),
        "ns_rate": ("NOMAD_TPU_NS_RATE", float, DEFAULT_NS_RATE),
        "ns_burst": ("NOMAD_TPU_NS_BURST", float, DEFAULT_NS_BURST),
        "brownout_high": ("NOMAD_TPU_BROWNOUT_HIGH", float, 0.75),
        "brownout_low": ("NOMAD_TPU_BROWNOUT_LOW", float, 0.25),
        "brownout_after_s": ("NOMAD_TPU_BROWNOUT_AFTER_S", float, 1.0),
        "margin": ("NOMAD_TPU_SLO_MARGIN", float, 0.6),
        # SLO burn-rate accounting (ISSUE 15): the availability
        # objective over "batch met the p99 latency target", and the
        # SRE-workbook fast/slow window pair
        "slo_objective": ("NOMAD_TPU_SLO_OBJECTIVE", float, 0.999),
        "slo_fast_window_s": ("NOMAD_TPU_SLO_FAST_WINDOW_S", float,
                              60.0),
        "slo_fast_burn": ("NOMAD_TPU_SLO_FAST_BURN", float, 14.0),
        "slo_slow_window_s": ("NOMAD_TPU_SLO_SLOW_WINDOW_S", float,
                              600.0),
        "slo_slow_burn": ("NOMAD_TPU_SLO_SLOW_BURN", float, 2.0),
        # scale-out plane (ISSUE 17): broker sharding, dequeue worker
        # count, raft group-commit width, cross-worker solve fusion
        "broker_shards": ("NOMAD_TPU_BROKER_SHARDS", int, 1),
        "num_workers": ("NOMAD_TPU_NUM_WORKERS", int, 2),
        "group_commit": ("NOMAD_TPU_GROUP_COMMIT", int, 8),
        "coordinator": ("NOMAD_TPU_COORDINATOR", int, 1),
        # double-buffered coordinator pipelining (ISSUE 19): dispatch
        # round b+1 while round b's device solve is in flight
        "pipeline": ("NOMAD_TPU_PIPELINE", int, 1),
        # leader soft-pause fraction of workers; -1 = auto (0 once the
        # broker is sharded — pausing dequeue parallelism defeats shard
        # homing — else the reference's 3/4)
        "worker_pause_fraction": ("NOMAD_TPU_WORKER_PAUSE_FRACTION",
                                  float, -1.0),
        # lane-parallel fused solve (ISSUE 20): starting lane width of
        # the chunked scan-of-vmap (1 = the serial scan, bit-for-bit),
        # the adaptive controller's pow2 ceiling, and its widen/narrow
        # bounce-rate thresholds (fractions of lane placements bounced
        # to STATUS_RETRY by the cross-lane revalidation)
        "fused_lanes": ("NOMAD_TPU_FUSED_LANES", int, 1),
        "max_lanes": ("NOMAD_TPU_MAX_LANES", int, 8),
        "lane_widen_below": ("NOMAD_TPU_LANE_WIDEN_BELOW", float, 0.05),
        "lane_narrow_above": ("NOMAD_TPU_LANE_NARROW_ABOVE", float,
                              0.25),
    }

    def __init__(self, adaptive: bool = True,
                 overrides: Optional[dict] = None):
        o = overrides or {}
        k = {}
        for name, (env, typ, default) in self.KNOBS.items():
            if name in o:
                k[name] = typ(o[name])
            elif env in os.environ:
                k[name] = (_env_float(env, default) if typ is float
                           else _env_int(env, default))
            else:
                k[name] = default
        self.adaptive = bool(o.get("adaptive", adaptive))
        self.bypass_priority = k["bypass_priority"]
        self.slo_budget_s = k["slo_budget_s"]
        self.max_batch = k["max_batch"]
        self.broker_shards = max(1, k["broker_shards"])
        self.num_workers = max(1, k["num_workers"])
        self.group_commit = max(1, k["group_commit"])
        self.coordinator = bool(k["coordinator"])
        self.pipeline = bool(k["pipeline"])
        self.worker_pause_fraction = k["worker_pause_fraction"]
        self.fused_lanes = max(1, k["fused_lanes"])
        self.max_lanes = max(1, k["max_lanes"])
        self.lane_widen_below = k["lane_widen_below"]
        self.lane_narrow_above = k["lane_narrow_above"]
        self.solve_model = EwmaSolveModel()
        self.batch_controller = BatchController(
            self.solve_model, slo_budget_s=k["slo_budget_s"],
            max_batch=k["max_batch"], margin=k["margin"])
        self.admission = AdmissionController(
            max_pending=k["max_pending"],
            protect_priority=k["bypass_priority"],
            ns_rate=k["ns_rate"], ns_burst=k["ns_burst"],
            brownout_high=k["brownout_high"],
            brownout_low=k["brownout_low"],
            brownout_after_s=k["brownout_after_s"])
        from ..telemetry.slo import SloBurnTracker
        from ..utils.metrics import global_metrics
        from ..utils.tracing import global_mesh_events
        self.burn = SloBurnTracker(
            objective=k["slo_objective"],
            fast_window_s=int(k["slo_fast_window_s"]),
            fast_burn=k["slo_fast_burn"],
            slow_window_s=int(k["slo_slow_window_s"]),
            slow_burn=k["slo_slow_burn"],
            events=global_mesh_events, metrics=global_metrics)

    def note_device_solve(self, n_evals: int, device_s: float) -> None:
        """Feed the batch-sizing model the DEVICE-solve time of a fused
        round, not its end-to-end wall.  Under the pipelined coordinator
        a round's wall clock includes waiting out the previous round's
        device occupancy plus reconcile/pack/plan-build overlap — feeding
        that into `EwmaSolveModel` would make `predict()` roughly 2x the
        marginal cost of one more batch, and the `BatchController` close
        rule would over-drain (every candidate blows the inflated budget,
        flipping to DRAIN mode under moderate load).  The SLO burn
        accounting (`observe_batch`) still sees end-to-end wall — the
        eval's latency is what it is — only the *sizing* model narrows
        to the device stage."""
        self.solve_model.observe(n_evals, device_s)

    def observe_batch(self, n_evals: int, wall_s: float) -> None:
        """One solved batch's SLO verdict: every eval in a batch that
        lands inside the latency budget is `good`, a blown batch
        charges all its evals to the error budget (the batch IS the
        latency unit — its evals waited on the same dispatch)."""
        n = max(int(n_evals), 1)
        if wall_s <= self.slo_budget_s:
            self.burn.observe(good=n)
        else:
            self.burn.observe(bad=n)

    def stats(self) -> dict:
        return {
            "adaptive": self.adaptive,
            "slo_budget_s": self.slo_budget_s,
            "max_batch": self.max_batch,
            "broker_shards": self.broker_shards,
            "num_workers": self.num_workers,
            "group_commit": self.group_commit,
            "coordinator": self.coordinator,
            "pipeline": self.pipeline,
            "fused_lanes": self.fused_lanes,
            "max_lanes": self.max_lanes,
            "last_target_batch": self.batch_controller.last_target(),
            "model_observations": self.solve_model.observations(),
            "admission": self.admission.stats(),
            "slo": self.burn.status(),
        }


# ===================================================================
# Cross-region admission spillover (ISSUE 13)
# ===================================================================

#: spillover SLO margin: a region "meets SLO" when its predicted
#: backlog-clear time fits inside slo_budget_s * margin
DEFAULT_SPILL_MARGIN = 0.8
#: relative cost of placing one eval in a region (WAN egress, energy,
#: $/chip-hour); the router prefers cheaper regions at equal health
DEFAULT_REGION_COST = 1.0


class RegionServingState:
    """One region's serving-tier view for the spillover router: its
    own EWMA solve model (regions differ in mesh width and load) and
    admission controller, plus the last reported ready-queue depth."""

    def __init__(self, name: str, cost: float = DEFAULT_REGION_COST,
                 model: Optional[EwmaSolveModel] = None,
                 admission: Optional[AdmissionController] = None):
        self.name = str(name)
        self.cost = float(cost)
        self.model = model if model is not None else EwmaSolveModel()
        self.admission = (admission if admission is not None
                          else AdmissionController())
        self._lock = threading.Lock()
        self._ready = 0
        self.live = True

    def note_ready(self, n: int) -> None:
        with self._lock:
            self._ready = max(int(n), 0)

    def ready(self) -> int:
        with self._lock:
            return self._ready

    def browned_out(self) -> bool:
        """Brownout watermark view: the controller's latched state OR
        the instantaneous high watermark (the router must not keep
        feeding a region in the `brownout_after_s` grace window)."""
        a = self.admission
        return (a.brownout_active()
                or self.ready() >= a.brownout_high * a.max_pending)

    def meets_slo(self, n_evals: int, budget_s: float) -> bool:
        return self.model.predict(self.ready() + max(n_evals, 1)) \
            <= budget_s


class WanLatencyModel:
    """Modeled per-region-pair WAN round-trip latency, seeded jitter.

    Cross-region placement in the real federation pays a WAN RPC
    before the eval lands in the remote broker; the router's SLO math
    and the `--multiregion` bench leg should pay that cost too, or
    spillover looks free and the router over-spills.  Latency is
    symmetric per unordered pair, zero within a region, and jittered
    from a seeded RNG so two runs with the same seed see identical
    delay sequences (the chaos plane's determinism rule: no wall
    clocks, no unseeded randomness).

    `expected()` is the jitter-free base — what the ROUTING decision
    subtracts from the SLO budget when weighing a remote region.
    `sample()` draws one jittered delay — what the SIMULATION adds to
    an eval's completion time after routing."""

    def __init__(self, default_s: float = 0.08, jitter: float = 0.25,
                 seed: int = 0x3A21):
        import random as _random
        self.default_s = float(default_s)
        self.jitter = float(jitter)
        self._pairs: Dict[frozenset, float] = {}
        self._rng = _random.Random(seed)
        self._lock = threading.Lock()
        self._samples = 0

    def set_pair(self, a: str, b: str, base_s: float) -> None:
        with self._lock:
            self._pairs[frozenset((str(a), str(b)))] = float(base_s)

    def expected(self, src: Optional[str], dst: str) -> float:
        """Jitter-free base latency for routing math (0 in-region or
        when the source region is unknown — no WAN hop to model)."""
        if not src or src == dst:
            return 0.0
        with self._lock:
            return self._pairs.get(frozenset((str(src), str(dst))),
                                   self.default_s)

    def sample(self, src: Optional[str], dst: str) -> float:
        """One jittered delay draw for the latency simulation."""
        base = self.expected(src, dst)
        if base <= 0.0:
            return 0.0
        with self._lock:
            self._samples += 1
            return base * (1.0 + self.jitter
                           * (2.0 * self._rng.random() - 1.0))

    def stats(self) -> dict:
        with self._lock:
            return {"default_s": self.default_s, "jitter": self.jitter,
                    "pairs": {"|".join(sorted(k)): v
                              for k, v in self._pairs.items()},
                    "samples": self._samples}


class SpilloverRouter:
    """Admission-tier cross-region spillover (ISSUE 13).

    Stock Nomad's region forwarding (nomad/rpc.go `forward`) ships an
    RPC to the job's HOME region and stops there — a browned-out home
    region just queues deeper.  This router places NEW work across the
    federation: the home region keeps the job while it is healthy and
    meets SLO (per-region EWMA solve model over the reported backlog),
    overflow goes to the cheapest sibling region meeting SLO when the
    home brownout watermark trips, and only when EVERY live region is
    browned out does the eval land in the router's shed lane — parked,
    never dropped, readmitted by `drain_shed` once any region drains.

    Region membership is gossip-driven: plug `on_join` / `on_fail`
    into the serf WAN pool (membership.gossip.GossipAgent); they feed
    the optional RegionDirectory (the federation membership table) and
    flip region liveness here.  Knobs follow the ServingTier pattern:
    overrides > NOMAD_TPU_* env > defaults."""

    #: knob -> (env var, type, default)
    KNOBS = {
        "slo_budget_s": ("NOMAD_TPU_SLO_BUDGET_S", float,
                         DEFAULT_SLO_BUDGET_S),
        "spill_margin": ("NOMAD_TPU_SPILL_MARGIN", float,
                         DEFAULT_SPILL_MARGIN),
        "region_cost": ("NOMAD_TPU_REGION_COST", float,
                        DEFAULT_REGION_COST),
        "max_pending": ("NOMAD_TPU_MAX_PENDING", int,
                        DEFAULT_MAX_PENDING),
    }

    def __init__(self, regions: Optional[Dict[str, float]] = None,
                 overrides: Optional[dict] = None,
                 directory=None, event_log=None, wan_model=None):
        o = overrides or {}
        k = {}
        for name, (env, typ, default) in self.KNOBS.items():
            if name in o:
                k[name] = typ(o[name])
            elif env in os.environ:
                k[name] = (_env_int(env, default) if typ is int
                           else _env_float(env, default))
            else:
                k[name] = default
        self.slo_budget_s = k["slo_budget_s"]
        self.spill_margin = k["spill_margin"]
        self.default_cost = k["region_cost"]
        self.max_pending = k["max_pending"]
        self.directory = directory
        #: optional WanLatencyModel — when set, remote candidates are
        #: judged against the SLO budget minus the modeled WAN hop, and
        #: wan_delay() lets simulations charge the jittered transfer
        self.wan_model = wan_model
        if event_log is None:
            from ..utils.tracing import global_mesh_events
            event_log = global_mesh_events
        self.event_log = event_log
        self._lock = threading.Lock()
        self._regions: Dict[str, RegionServingState] = {}
        self._shed_lane: List = []
        self._counts = {"home": 0, "cheapest": 0, "spillover": 0,
                        "slo_miss": 0, "shed": 0, "readmitted": 0}
        for name, cost in (regions or {}).items():
            self.add_region(name, cost)

    # ------------------------------------------------------ membership
    def add_region(self, name: str,
                   cost: Optional[float] = None) -> RegionServingState:
        with self._lock:
            rs = self._regions.get(name)
            if rs is None:
                rs = RegionServingState(
                    name, self.default_cost if cost is None else cost,
                    admission=AdmissionController(
                        max_pending=self.max_pending))
                self._regions[name] = rs
            elif cost is not None:
                rs.cost = float(cost)
            rs.live = True
            return rs

    def region(self, name: str) -> RegionServingState:
        with self._lock:
            return self._regions[name]

    def regions(self) -> List[str]:
        with self._lock:
            return sorted(r for r, rs in self._regions.items()
                          if rs.live)

    def on_join(self, member) -> None:
        """Serf WAN-gossip join: a member of region X comes up — the
        region (re)enters the routing table."""
        region = getattr(member, "region", None) or "global"
        if self.directory is not None:
            self.directory.on_join(member)
        self.add_region(str(region))

    def on_fail(self, member) -> None:
        """Serf WAN-gossip fail: when a region's LAST member dies the
        region leaves the routing table (individual member loss keeps
        it live — the mesh supervisor handles shard recovery)."""
        region = str(getattr(member, "region", None) or "global")
        if self.directory is not None:
            self.directory.on_fail(member)
            gone = region not in self.directory.regions()
        else:
            gone = True                # no membership view: fail fast
        if gone:
            with self._lock:
                rs = self._regions.get(region)
                if rs is not None:
                    rs.live = False

    # --------------------------------------------------------- routing
    def route(self, ev, home: Optional[str] = None,
              n_evals: int = 1) -> Tuple[Optional[str], str]:
        """Pick the region for one arriving eval.  Returns
        (region_name, cause); cause is "home" (healthy home region),
        "cheapest" (no home given), "spillover" (home browned out or
        past SLO — cheapest sibling meeting SLO), "slo_miss" (no
        region meets SLO but one is un-browned: admit late rather
        than park), or "shed" with region None (every live region
        browned out: the eval is in the shed lane — never dropped)."""
        budget = self.slo_budget_s * self.spill_margin
        with self._lock:
            live = sorted((rs for rs in self._regions.values()
                           if rs.live),
                          key=lambda rs: (rs.cost, rs.name))
        if not live:
            with self._lock:
                self._shed_lane.append(ev)
                self._counts["shed"] += 1
            return None, "shed"
        home_rs = next((rs for rs in live if rs.name == home), None)
        if home_rs is not None and not home_rs.browned_out() \
                and home_rs.meets_slo(n_evals, budget):
            return self._picked(home_rs, "home")
        # remote candidates must clear SLO with the modeled WAN hop
        # already spent — otherwise spillover looks free and a distant
        # region wins over a slightly-loaded near one
        fits = [rs for rs in live if not rs.browned_out()
                and rs.meets_slo(n_evals,
                                 budget - self._wan_s(home, rs.name))]
        if fits:
            cause = "cheapest" if home_rs is None else "spillover"
            return self._picked(fits[0], cause)
        unbrowned = [rs for rs in live if not rs.browned_out()]
        if unbrowned:
            # admit late at the least-loaded un-browned region: an
            # SLO miss beats parking the eval behind a drain
            pick = min(unbrowned,
                       key=lambda rs: (rs.model.predict(
                           rs.ready() + max(n_evals, 1)), rs.cost,
                           rs.name))
            return self._picked(pick, "slo_miss")
        with self._lock:
            self._shed_lane.append(ev)
            self._counts["shed"] += 1
        self.event_log.record("region.shed",
                              home=home or "", depth=len(
                                  self._shed_lane))
        return None, "shed"

    def _wan_s(self, home: Optional[str], region: str) -> float:
        if self.wan_model is None:
            return 0.0
        return self.wan_model.expected(home, region)

    def wan_delay(self, src: Optional[str], dst: str) -> float:
        """One jittered WAN transfer-delay draw for the chosen route
        (0 without a model or for in-region placement) — charged by
        the latency simulation, not by routing."""
        if self.wan_model is None:
            return 0.0
        return self.wan_model.sample(src, dst)

    def _picked(self, rs: RegionServingState,
                cause: str) -> Tuple[str, str]:
        with self._lock:
            self._counts[cause] = self._counts.get(cause, 0) + 1
        if cause == "spillover":
            self.event_log.record("region.spill", region=rs.name)
        return rs.name, cause

    # ----------------------------------------------------------- drain
    def drain_shed(self, max_n: int = DEFAULT_MAX_BATCH
                   ) -> List[Tuple[object, str]]:
        """Readmit parked evals once any region has drained: returns
        up to max_n (eval, region) pairs routed to un-browned regions
        meeting SLO (the shed lane keeps the rest — still never
        dropped)."""
        out: List[Tuple[object, str]] = []
        budget = self.slo_budget_s * self.spill_margin
        while len(out) < max_n:
            with self._lock:
                if not self._shed_lane:
                    break
                live = sorted(
                    (rs for rs in self._regions.values()
                     if rs.live and not rs.browned_out()),
                    key=lambda rs: (rs.cost, rs.name))
                fits = [rs for rs in live
                        if rs.meets_slo(1, budget)] or live
                if not fits:
                    break
                ev = self._shed_lane.pop(0)
                self._counts["readmitted"] += 1
            out.append((ev, fits[0].name))
        return out

    def shed_depth(self) -> int:
        with self._lock:
            return len(self._shed_lane)

    def note_solve(self, region: str, n_evals: int,
                   wall_s: float) -> None:
        """Feed one region's observed solve into its EWMA model."""
        self._regions[region].model.observe(n_evals, wall_s)

    def stats(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            shed_depth = len(self._shed_lane)
            regions = {
                name: {"cost": rs.cost, "live": rs.live,
                       "ready": rs.ready(),
                       "browned_out": rs.browned_out(),
                       "model_observations":
                           rs.model.observations()}
                for name, rs in self._regions.items()}
        out = {"slo_budget_s": self.slo_budget_s,
               "spill_margin": self.spill_margin,
               "routed": counts, "shed_lane_depth": shed_depth,
               "regions": regions}
        if self.wan_model is not None:
            out["wan"] = self.wan_model.stats()
        return out
