"""Server: replicated state + broker + workers + plan applier.

This is the control-plane container (reference: nomad/server.go Server +
the FSM apply paths in nomad/fsm.go). Every write is proposed as a typed
entry through a raft node (nomad_tpu/raft) and applied to the state
store by the FSM on commit — identically on leader and followers. The
default deployment is a bootstrapped single-node cluster (immediate
commits, optionally durable via data_dir); multi-server clusters share a
transport and elect a leader, and only the leader runs the broker,
workers, heartbeater, watchers and plan applier
(reference: leader.go:197 establishLeadership / :1018 revokeLeadership).
"""
from __future__ import annotations

import threading
import time as _time
from typing import Dict, Iterable, List, Optional, Tuple

from ..raft import NotLeaderError, RaftConfig, RaftNode, StateFSM
from ..utils.codec import to_wire

from ..state.store import StateStore
from ..structs import (ALLOC_CLIENT_FAILED, CORE_JOB_PRIORITY,
                       EVAL_STATUS_PENDING,
                       EVAL_TRIGGER_DEPLOYMENT_PROMOTION,
                       EVAL_TRIGGER_DEPLOYMENT_WATCHER,
                       EVAL_TRIGGER_NODE_DRAIN,
                       EVAL_TRIGGER_JOB_DEREGISTER,
                       EVAL_TRIGGER_JOB_REGISTER, EVAL_TRIGGER_NODE_UPDATE,
                       EVAL_TRIGGER_RETRY_FAILED_ALLOC, JOB_TYPE_CORE,
                       JOB_TYPE_SERVICE, NODE_STATUS_DOWN, NODE_STATUS_READY,
                       SCHEDULERS, Allocation, Evaluation, Job, Node, Plan,
                       PlanResult)
from ..utils.ids import generate_uuid
from ..utils.timetable import TimeTable
from .blocked_evals import BlockedEvals
from .eval_broker import EvalBroker
from .heartbeat import NodeHeartbeater
from .periodic import PeriodicDispatcher
from .plan_apply import PlanApplier
from .plan_queue import PlanQueue
from .worker import Worker


class JobValidationError(ValueError):
    """A job failed structural validation at registration (maps to
    HTTP 400, distinct from the check-and-set index conflict's 409)."""


class Server:
    def __init__(self, num_workers: Optional[int] = None,
                 enabled_schedulers: Optional[List[str]] = None,
                 batch_size: int = 8,
                 min_heartbeat_ttl_s: float = 10.0,
                 heartbeat_grace_s: float = 10.0,
                 failover_heartbeat_ttl_s: float = 300.0,
                 gc_interval_s: float = 300.0,
                 job_gc_threshold_s: float = 4 * 3600.0,
                 eval_gc_threshold_s: float = 3600.0,
                 node_gc_threshold_s: float = 24 * 3600.0,
                 deployment_gc_threshold_s: float = 3600.0,
                 raft_config: Optional[RaftConfig] = None,
                 raft_transport=None,
                 serving_config: Optional[dict] = None):
        self.store = StateStore()
        self.fsm = StateFSM(self.store)
        if raft_config is None:
            raft_config = RaftConfig(node_id="server-1", peers=[])
        if raft_transport is None:
            from ..raft import InProcTransport
            raft_transport = InProcTransport()
        self.raft = RaftNode(raft_config, self.fsm, raft_transport,
                             on_leader=self._establish_leadership,
                             on_follower=self._revoke_leadership)
        self._multi = len(raft_config.peers) > 1
        # serving tier (ISSUE 6): adaptive micro-batching + admission
        # control shared by every worker and the eval-ingress path;
        # `serving_config` (agent `server { serving { ... } }` stanza)
        # overrides env overrides defaults.  {"adaptive": False} pins
        # the fixed batch_size dequeue (the pre-serving behavior) while
        # keeping admission bounded.  Built before the broker: the tier
        # owns the scale-out knobs (shards/workers/group commit).
        from .serving import ServingTier
        self.serving = ServingTier(overrides=serving_config)
        self.broker = EvalBroker(shards=self.serving.broker_shards)
        self.blocked_evals = BlockedEvals(self.broker)
        self.plan_queue = PlanQueue()
        self.batch_size = batch_size
        # telemetry tick state (ISSUE 15): last counter snapshots for
        # per-beat rate series + the most recent fleet health report
        # served at /v1/telemetry/health (assigned whole — readers on
        # the HTTP thread see either the old or the new dict)
        self._telemetry_state: Dict[str, float] = {}
        self._telemetry_lock = threading.Lock()
        self._last_health: Optional[dict] = None
        self.planner = PlanApplier(self.plan_queue, self.store,
                                   self._apply_plan, self._create_evals,
                                   apply_async_fn=self._apply_plan_async,
                                   apply_batch_async_fn=(
                                       self._apply_plan_batch_async),
                                   group_commit=self.serving.group_commit)
        self.enabled_schedulers = enabled_schedulers or [
            s for s in SCHEDULERS if s != JOB_TYPE_CORE]
        # every worker must also drain the core queue or GC evals pile up
        # forever (reference: server.go setupWorkers forces JobTypeCore into
        # each worker's enabled set)
        worker_types = list(self.enabled_schedulers)
        if JOB_TYPE_CORE not in worker_types:
            worker_types.append(JOB_TYPE_CORE)
        if num_workers is None:
            num_workers = self.serving.num_workers
        self.workers = [Worker(self, worker_types, index=i)
                        for i in range(num_workers)]
        # cross-worker fused solves (ISSUE 17): bulk batches from every
        # worker coalesce into one device wave; express lane stays
        # single-solve inside the worker
        self.solve_coordinator = None
        if self.serving.coordinator and num_workers > 1:
            from ..scheduler.fleet import SolveCoordinator
            self.solve_coordinator = SolveCoordinator(
                self, pipeline=self.serving.pipeline)
        self.heartbeater = NodeHeartbeater(
            self._on_heartbeat_expired,
            min_heartbeat_ttl_s=min_heartbeat_ttl_s,
            heartbeat_grace_s=heartbeat_grace_s,
            failover_heartbeat_ttl_s=failover_heartbeat_ttl_s)
        self.periodic = PeriodicDispatcher(self)
        from .deployment_watcher import DeploymentWatcher
        self.deployment_watcher = DeploymentWatcher(self)
        from .drainer import NodeDrainer
        self.drainer = NodeDrainer(self)
        self.time_table = TimeTable()
        self.gc_interval_s = gc_interval_s
        self.job_gc_threshold_s = job_gc_threshold_s
        self.eval_gc_threshold_s = eval_gc_threshold_s
        self.node_gc_threshold_s = node_gc_threshold_s
        self.deployment_gc_threshold_s = deployment_gc_threshold_s
        self._gc_timer: Optional[threading.Thread] = None
        self._metrics_timer: Optional[threading.Thread] = None
        self._started = False
        self._stop_reapers = threading.Event()
        self._dup_reaper: Optional[threading.Thread] = None
        self._cas_lock = threading.Lock()
        if not self._multi:
            # single-node deployments can accept writes immediately
            # (pre-raft callers constructed a Server and wrote to it
            # without start()); leader services still wait for start()
            self.raft.bootstrap_single(defer_events=True)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Join the raft cluster. Single-node deployments bootstrap and
        become leader synchronously (existing callers see the same
        behavior as before); multi-node members run the election and
        leader services follow leadership transitions."""
        if self._multi:
            self.raft.start()
        else:
            self.raft.fire_pending_role_events()

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    def _establish_leadership(self) -> None:
        """Enable leader-only services + workers
        (reference: leader.go:197 establishLeadership)."""
        self.broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.plan_queue.set_enabled(True)
        self.planner.start()
        for w in self.workers:
            w.start()
        # Reserve leader CPU for raft + plan application by pausing a
        # fraction of the scheduling workers (reference: leader.go:206-212
        # pauses len(s.workers)/4*3 while leader).  Pausing directly caps
        # dequeue parallelism, which defeats the sharded broker — so the
        # fraction is a serving knob: -1 (auto) pauses none once the
        # broker is sharded (shard homes need their workers) and keeps
        # the reference 3/4 otherwise; at least one worker always runs
        # so scheduling can't stall.
        frac = self.serving.worker_pause_fraction
        if frac < 0.0:
            n_pause = 0 if self.serving.broker_shards > 1 \
                else len(self.workers) // 4 * 3
        else:
            n_pause = int(len(self.workers) * min(frac, 1.0))
        if n_pause >= len(self.workers):
            n_pause = len(self.workers) - 1
        for w in self.workers[:max(0, n_pause)]:
            w.paused.set()
        self._stop_reapers.clear()
        self._dup_reaper = threading.Thread(
            target=self._reap_dup_blocked_evals, daemon=True)
        self._dup_reaper.start()
        # grant known live nodes the failover TTL before expecting fresh
        # heartbeats (leader.go:296 initializeHeartbeatTimers)
        self.heartbeater.set_enabled(True)
        self.heartbeater.initialize(
            n.id for n in self.store.nodes() if not n.terminal_status())
        self.deployment_watcher.set_enabled(True)
        self.drainer.set_enabled(True)
        # periodic jobs resume their schedules (leader.go restorePeriodicDispatcher)
        self.periodic.set_enabled(True)
        for job in self.store.jobs():
            if job.is_periodic():
                self.periodic.add(job)
        self._gc_timer = threading.Thread(target=self._schedule_periodic_gc,
                                          daemon=True)
        self._gc_timer.start()
        # broker gauges must not freeze while every worker is paused or
        # draining (the worker loop was their only exporter): a leader
        # timer re-exports them on a fixed beat, idempotently — gauges
        # are plain sets, so the two exporters never conflict
        self._metrics_timer = threading.Thread(
            target=self._export_metrics_loop, daemon=True)
        self._metrics_timer.start()
        self._started = True
        self._restore_evals()

    def stop(self) -> None:
        self._revoke_leadership()
        # join workers so no straggler proposes after stop() returns (a
        # mid-eval worker would otherwise race the caller's view of the
        # final state)
        for w in self.workers:
            if w.is_alive():
                w.join(timeout=5.0)
        self.raft.stop()

    def _revoke_leadership(self) -> None:
        self.heartbeater.set_enabled(False)
        self.deployment_watcher.set_enabled(False)
        self.drainer.set_enabled(False)
        self.periodic.set_enabled(False)
        self._stop_reapers.set()
        for w in self.workers:
            w.paused.clear()
            w.shutdown()
        self.planner.stop()
        self.plan_queue.set_enabled(False)
        self.broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        self._started = False

    def _reap_dup_blocked_evals(self) -> None:
        """Cancel blocked evals displaced by a newer eval for the same job
        (reference: leader.go:625 reapDupBlockedEvaluations)."""
        import copy
        from ..structs import EVAL_STATUS_CANCELLED
        ticks = 0
        while not self._stop_reapers.is_set():
            ticks += 1
            if ticks % 10 == 0:
                self._autopilot_reconcile()
            dups = self.blocked_evals.get_duplicates(timeout=0.2)
            if not dups:
                continue
            cancelled = []
            for ev in dups:
                e2 = copy.copy(ev)
                e2.status = EVAL_STATUS_CANCELLED
                e2.status_description = \
                    "cancelled due to duplicate blocked evaluation"
                cancelled.append(e2)
            self.upsert_evals(cancelled)

    def _restore_evals(self) -> None:
        """Re-enqueue non-terminal evals from state (leader.go:245).

        Blocked evals are RE-ENQUEUED rather than re-blocked: the
        missed-unblock protection (blocked_evals.py) keys off an
        in-memory map of capacity-change indexes that an incoming
        leader doesn't have, so a blocked eval whose capacity arrived
        before the leadership change would otherwise wait forever.  One
        fresh scheduling pass either places it or re-blocks it against
        live capacity state."""
        import copy
        from ..structs import EVAL_STATUS_PENDING
        for ev in list(self.store.evals()):
            if ev.should_enqueue():
                self.broker.enqueue(ev)
            elif ev.should_block():
                redo = copy.copy(ev)
                redo.status = EVAL_STATUS_PENDING
                self.broker.enqueue(redo)

    def _schedule_periodic_gc(self) -> None:
        """Leader timer enqueueing core GC evals (leader.go:513
        schedulePeriodic; the evals are broker-only, not persisted, to
        avoid duplication across restarts)."""
        from ..scheduler.core import (CORE_JOB_DEPLOYMENT_GC,
                                      CORE_JOB_EVAL_GC, CORE_JOB_JOB_GC,
                                      CORE_JOB_NODE_GC)
        while not self._stop_reapers.wait(self.gc_interval_s):
            for kind in (CORE_JOB_EVAL_GC, CORE_JOB_NODE_GC,
                         CORE_JOB_JOB_GC, CORE_JOB_DEPLOYMENT_GC):
                self.broker.enqueue(self._core_job_eval(kind))

    #: server-side broker-gauge export beat (seconds)
    METRICS_EXPORT_INTERVAL_S = 1.0

    #: fleet health sample cadence, in export beats (the host-twin
    #: reduction walks every node plane; 1 Hz would be wasteful on
    #: large fleets, 5 s tracks churn fine)
    HEALTH_SAMPLE_EVERY = 5

    def _export_metrics_loop(self) -> None:
        beats = 0
        while not self._stop_reapers.wait(self.METRICS_EXPORT_INTERVAL_S):
            self.broker.export_metrics()
            beats += 1
            try:
                self._telemetry_tick(beats)
            except Exception:
                # telemetry must never kill the export beat — the
                # broker gauges above are load-bearing for operators
                from ..utils.metrics import global_metrics as _m
                _m.incr_counter("telemetry.tick_error")

    def _telemetry_tick(self, beats: int) -> None:
        """Feed the multi-resolution series store on the export beat
        (ISSUE 15): broker depth/age, admission rates (counter deltas
        per beat), mesh event rate, and — every HEALTH_SAMPLE_EVERY
        beats — a fleet health sample over the worker solver's
        resident world, published for /v1/telemetry/health."""
        from ..telemetry.series import global_series as _s
        from ..utils.metrics import global_metrics as _m
        from ..utils.tracing import global_mesh_events as _ev
        st = self._telemetry_state
        _s.record("broker.ready_depth", float(self.broker.ready_count()))
        _s.record("broker.oldest_age_s",
                  float(self.broker.oldest_ready_age()))
        adm = self.serving.admission.stats()

        def _rate(key: str) -> Optional[float]:
            cur = float(adm.get(key, 0))
            prev = st.get("adm_" + key)
            st["adm_" + key] = cur
            return None if prev is None else cur - prev

        offered, admitted, shed = (_rate("offered"), _rate("admitted"),
                                   _rate("shed"))
        if offered is not None:
            _s.record("serving.offered_rate", offered)
        if admitted is not None:
            _s.record("serving.admitted_rate", admitted)
        if shed is not None:
            _s.record("serving.shed_rate", shed)
        _s.record("serving.brownout",
                  1.0 if self.serving.admission.brownout_active() else 0.0)
        seq = _ev.last_seq
        prev = st.get("mesh_seq")
        if prev is not None:
            _s.record("mesh.event_rate", float(seq - prev))
        st["mesh_seq"] = seq
        if beats % self.HEALTH_SAMPLE_EVERY != 0 or not self.workers:
            return
        solver = self.workers[0]._solver   # sample only an EXISTING
        if solver is None:                 # solver; never build one here
            return
        hc = solver.health_counters()
        if hc is None:
            return
        report = hc.report()
        report["sampled_at"] = _time.time()
        with self._telemetry_lock:
            self._last_health = report
        _m.set_gauge("health.nodes_busy", float(hc.nodes_busy))
        _m.set_gauge("health.nodes_stranded", float(hc.nodes_stranded))
        _m.set_gauge("health.fragmentation_index",
                     hc.fragmentation_index())
        _m.set_gauge("health.spread_violations",
                     float(hc.spread_violations()))
        _m.set_gauge("health.ev_slots", float(hc.ev_slots))
        _s.record("health.nodes_busy", float(hc.nodes_busy))
        _s.record("health.fragmentation_index",
                  hc.fragmentation_index())
        _s.record("health.utilization",
                  float(report["utilization"]))

    def last_health(self) -> Optional[dict]:
        """Most recent fleet health report from the telemetry tick
        (None until a resident world exists to sample)."""
        with self._telemetry_lock:
            return self._last_health

    def _core_job_eval(self, kind: str) -> Evaluation:
        index = self.store.latest_index()
        return Evaluation(
            namespace="-", type=JOB_TYPE_CORE, job_id=f"{kind}:{index}",
            priority=CORE_JOB_PRIORITY, status=EVAL_STATUS_PENDING,
            triggered_by="scheduled")

    def force_gc(self) -> Evaluation:
        """Run every GC pass with the threshold maxed (core_sched.go:67)."""
        from ..scheduler.core import CORE_JOB_FORCE_GC
        ev = self._core_job_eval(CORE_JOB_FORCE_GC)
        self.broker.enqueue(ev)
        return ev

    # -------------------------------------------------------- write paths
    def _propose(self, etype: str, payload) -> int:
        """Raft-apply one typed entry; returns its log index (== the
        store modify index the FSM wrote it at)."""
        index = self.raft.propose(etype, payload)
        self.time_table.witness(index)
        return index

    def register_node(self, node: Node) -> int:
        existing = self.store.node_by_id(node.id)
        index = self._propose("node_upsert", {"node": to_wire(node)})
        # new capacity unblocks waiters keyed by the node's class
        if node.ready():
            self.blocked_evals.unblock(node.computed_class, index)
        if existing is None and node.ready():
            self._create_node_evals_for_system_jobs(node, index)
        self.heartbeater.reset(node.id)
        return index

    def node_heartbeat(self, node_id: str) -> Optional[float]:
        """Client liveness ping; returns the TTL before the next expected
        heartbeat, or None for unknown nodes (the client must re-register).
        A down node that resumes heartbeating is restored to ready — in the
        reference the heartbeat IS Node.UpdateStatus(ready)
        (node_endpoint.go:373 + heartbeat.go:90)."""
        node = self.store.node_by_id(node_id)
        if node is None:
            return None
        if node.status == NODE_STATUS_DOWN:
            self.update_node_status(node_id, NODE_STATUS_READY)
        return self.heartbeater.reset(node_id)

    def _on_heartbeat_expired(self, node_id: str) -> None:
        """A node missed its TTL: mark it down, which fans out reschedule
        evals (reference: heartbeat.go:135 invalidateHeartbeat)."""
        node = self.store.node_by_id(node_id)
        if node is None or node.status == NODE_STATUS_DOWN:
            return
        self.update_node_status(node_id, NODE_STATUS_DOWN)

    def update_node_status(self, node_id: str, status: str) -> int:
        index = self._propose("node_status",
                              {"node_id": node_id, "status": status})
        node = self.store.node_by_id(node_id)
        if node is None:
            return index
        if status == NODE_STATUS_DOWN:
            self.heartbeater.clear(node_id)
            self._create_node_evals(node, index)
        elif status == NODE_STATUS_READY:
            self.blocked_evals.unblock(node.computed_class, index)
            self._create_node_evals_for_system_jobs(node, index)
            self.heartbeater.reset(node_id)
        return index

    def update_node_drain(self, node_id: str, drain_strategy,
                          mark_eligible: bool = False) -> int:
        # stamp the absolute force deadline at request time
        # (reference: node_endpoint.go UpdateDrain)
        if drain_strategy is not None and drain_strategy.deadline_s > 0 \
                and not drain_strategy.force_deadline:
            drain_strategy.force_deadline = \
                _time.time() + drain_strategy.deadline_s
        index = self._propose("node_drain", {
            "node_id": node_id,
            "drain_strategy": to_wire(drain_strategy)
            if drain_strategy is not None else None,
            "mark_eligible": mark_eligible})
        node = self.store.node_by_id(node_id)
        if node is not None:
            self._create_node_evals(node, index)
        return index

    def drain_allocs(self, alloc_ids: List[str]) -> int:
        """Mark allocs for migration and evaluate their jobs — the
        drainer's only write (reference: drainer.go drainAllocs ->
        Allocs.UpdateDesiredTransition)."""
        from ..structs import DesiredTransition
        index = self._propose("alloc_transition", {
            "alloc_ids": list(alloc_ids),
            "transition": to_wire(DesiredTransition(migrate=True))})
        evals: List[Evaluation] = []
        seen = set()
        for aid in alloc_ids:
            a = self.store.alloc_by_id(aid)
            if a is None:
                continue
            key = (a.namespace, a.job_id)
            if key in seen:
                continue
            seen.add(key)
            job = a.job or self.store.job_by_id(*key)
            evals.append(Evaluation(
                namespace=a.namespace, job_id=a.job_id,
                type=job.type if job else JOB_TYPE_SERVICE,
                priority=job.priority if job else 50,
                triggered_by=EVAL_TRIGGER_NODE_DRAIN,
                status=EVAL_STATUS_PENDING))
        self._create_evals(evals)
        return index

    def update_node_eligibility(self, node_id: str,
                                eligibility: str) -> int:
        """Node.UpdateEligibility analog (node_endpoint.go)."""
        index = self._propose("node_eligibility", {
            "node_id": node_id, "eligibility": eligibility})
        node = self.store.node_by_id(node_id)
        if node is not None and node.ready():
            self.blocked_evals.unblock(node.computed_class, index)
        return index

    def stop_alloc(self, alloc_id: str) -> Optional[Evaluation]:
        """Alloc.Stop analog: mark the alloc for migration and evaluate
        its job (alloc_endpoint.go AllocSpecificRequest stop)."""
        from ..structs import DesiredTransition
        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            return None
        self._propose("alloc_transition", {
            "alloc_ids": [alloc_id],
            "transition": to_wire(DesiredTransition(migrate=True))})
        job = alloc.job or self.store.job_by_id(alloc.namespace,
                                                alloc.job_id)
        ev = Evaluation(
            namespace=alloc.namespace, job_id=alloc.job_id,
            type=job.type if job else JOB_TYPE_SERVICE,
            priority=job.priority if job else 50,
            triggered_by="alloc-stop", status=EVAL_STATUS_PENDING)
        self._create_evals([ev])
        return ev

    def register_job(self, job: Job, enforce_index: bool = False,
                     check_index: int = 0) -> Optional[Evaluation]:
        job.canonicalize()
        # validate server-side so every path (HTTP, RPC, direct) is
        # covered (reference: job_endpoint.go Job.Register → Validate
        # runs in the RPC, not just the agent)
        errs = job.validate()
        if errs:
            raise JobValidationError(
                "job validation failed: " + "; ".join(errs))
        # _cas_lock keeps the check-and-set registration atomic across
        # concurrent registrars (reference: job_endpoint.go Job.Register
        # EnforceIndex runs inside the raft apply's serialization)
        with self._cas_lock:
            if enforce_index:
                existing = self.store.job_by_id(job.namespace, job.id)
                current = existing.job_modify_index if existing else 0
                if current != check_index:
                    raise ValueError(
                        f"job modify index mismatch: have {current}, "
                        f"want {check_index}")
            self._propose("job_upsert", {"job": to_wire(job)})
        # the FSM applied a decoded copy; re-read for the stamped indexes
        stored = self.store.job_by_id(job.namespace, job.id) or job
        # periodic parents and parameterized jobs are templates: tracked by
        # their dispatchers, never evaluated directly (job_endpoint.go:308)
        if stored.is_periodic():
            self.periodic.add(stored)
            return None
        if stored.is_parameterized():
            return None
        ev = Evaluation(
            namespace=stored.namespace, priority=stored.priority,
            type=stored.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER, job_id=stored.id,
            job_modify_index=stored.modify_index,
            status=EVAL_STATUS_PENDING)
        self._create_evals([ev])
        return ev

    def deregister_job(self, namespace: str, job_id: str,
                       purge: bool = False) -> Optional[Evaluation]:
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            return None
        if purge:
            self._propose("job_delete", {"namespace": namespace,
                                         "job_id": job_id})
        else:
            import copy
            j2 = copy.copy(job)
            j2.stop = True
            self._propose("job_upsert", {"job": to_wire(j2)})
        self.blocked_evals.untrack(namespace, job_id)
        self.periodic.remove(namespace, job_id)
        if job.is_periodic() or job.is_parameterized():
            return None
        ev = Evaluation(
            namespace=namespace, priority=job.priority, type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_DEREGISTER, job_id=job_id,
            status=EVAL_STATUS_PENDING)
        self._create_evals([ev])
        return ev

    def get_client_allocs(self, node_id: str, min_index: int,
                          timeout: float):
        """Blocking query for a node's allocations (reference:
        node_endpoint.go:924 Node.GetClientAllocs — index-filtered pull
        the client long-polls). Returns (allocs, index)."""
        deadline = _time.monotonic() + timeout
        while True:
            # capture the store head BEFORE the table check: a write landing
            # between the two reads then wakes wait_for_change immediately
            head = self.store.latest_index()
            index = self.store.table_index("allocs")
            if index > min_index:
                return self.store.allocs_by_node(node_id), index
            remain = deadline - _time.monotonic()
            if remain <= 0:
                return self.store.allocs_by_node(node_id), max(index,
                                                               min_index)
            # wait for any write past the head, then recheck the allocs
            # table index (other tables' writes wake us early)
            self.store.wait_for_change(head, remain)

    def update_allocs_from_client(self, updates: List[Allocation]) -> int:
        """Client status sync (reference: node_endpoint.go:1063
        Node.UpdateAlloc -> fsm.go:749)."""
        index = self._propose("allocs_client", {
            "updates": [to_wire(u) for u in updates]})
        evals: List[Evaluation] = []
        unblock_nodes = set()
        for upd in updates:
            alloc = self.store.alloc_by_id(upd.id)
            if alloc is None:
                continue
            if alloc.client_terminal_status():
                unblock_nodes.add(alloc.node_id)
            # failed allocs trigger a reschedule eval
            if upd.client_status == ALLOC_CLIENT_FAILED and alloc.job:
                tg = alloc.job.lookup_task_group(alloc.task_group)
                policy = tg.reschedule_policy if tg else None
                if policy and (policy.unlimited or policy.attempts > 0):
                    evals.append(Evaluation(
                        namespace=alloc.namespace, type=alloc.job.type,
                        priority=alloc.job.priority, job_id=alloc.job_id,
                        triggered_by=EVAL_TRIGGER_RETRY_FAILED_ALLOC,
                        status=EVAL_STATUS_PENDING))
        if evals:
            self._create_evals(evals)
        for nid in unblock_nodes:
            node = self.store.node_by_id(nid)
            if node is not None:
                self.blocked_evals.unblock(node.computed_class, index)
        return index

    # ----------------------------------------------------- eval plumbing
    def _create_evals(self, evals: List[Evaluation]) -> None:
        """Raft-apply eval upserts, then route to broker / blocked list
        (reference: fsm.go:680 handleUpsertedEval)."""
        if not evals:
            return
        from ..utils.tracing import global_tracer as _tr
        head = self.store.latest_index() + 1
        for ev in evals:
            if not ev.create_time:
                ev.create_time = _time.time()
            ev.modify_time = _time.time()
            ev.snapshot_index = ev.snapshot_index or head
        self._propose("evals_upsert",
                      {"evals": [to_wire(e) for e in evals]})
        # enqueue the FSM's stored copies (they carry the apply indexes)
        for ev in evals:
            stored = self.store.eval_by_id(ev.id) or ev
            if stored.should_enqueue():
                # flight-recorder root (ISSUE 10): the eval id IS the
                # trace id; every later lifecycle stage chains on this
                _tr.event(stored.id, "create", parent="",
                          job_id=stored.job_id,
                          namespace=stored.namespace,
                          priority=stored.priority, type=stored.type,
                          triggered_by=stored.triggered_by)
                # serving-tier admission gate (ISSUE 6): bounded broker
                # ingress with priority-aware shedding.  Shed evals park
                # in blocked_evals' shed lane — still persisted PENDING
                # in state, never dropped — and readmit on drain (the
                # worker's readmit tick).  Broker-internal re-enqueues
                # (nack redelivery, blocked promotion, delayed evals)
                # are not ingress and bypass this gate.
                admitted, cause = (
                    self.serving.admission.offer_ex(
                        stored, self.broker.ready_count())
                    if self.serving is not None else (True, ""))
                if not admitted:
                    _tr.event(stored.id, "admit", admitted=False,
                              shed_cause=cause)
                    self.blocked_evals.shed(stored)
                else:
                    _tr.event(stored.id, "admit", admitted=True)
                    self.broker.enqueue(stored)
            elif stored.should_block():
                self.blocked_evals.block(stored)

    def upsert_evals(self, evals: List[Evaluation]) -> None:
        self._create_evals(evals)

    def _create_node_evals(self, node: Node, index: int) -> None:
        """One eval per job with allocs on the node, plus system jobs
        (reference: node_endpoint.go:1348 createNodeEvals)."""
        evals: List[Evaluation] = []
        seen = set()
        for a in self.store.allocs_by_node(node.id):
            key = (a.namespace, a.job_id)
            if key in seen or a.terminal_status():
                continue
            seen.add(key)
            job = a.job or self.store.job_by_id(*key)
            evals.append(Evaluation(
                namespace=a.namespace, job_id=a.job_id,
                type=job.type if job else JOB_TYPE_SERVICE,
                priority=job.priority if job else 50,
                triggered_by=EVAL_TRIGGER_NODE_UPDATE, node_id=node.id,
                node_modify_index=node.modify_index,
                status=EVAL_STATUS_PENDING))
        self._create_evals(evals)

    def _create_node_evals_for_system_jobs(self, node: Node,
                                           index: int) -> None:
        evals = []
        for job in self.store.jobs():
            if job.is_system() and not job.stopped():
                evals.append(Evaluation(
                    namespace=job.namespace, job_id=job.id, type=job.type,
                    priority=job.priority,
                    triggered_by=EVAL_TRIGGER_NODE_UPDATE, node_id=node.id,
                    status=EVAL_STATUS_PENDING))
        self._create_evals(evals)

    # -------------------------------------------------------- deployments
    def apply_deployment_status_update(self, update,
                                       mark_stable=None) -> int:
        """Raft-apply a deployment status change; optionally mark the
        job version stable in the same apply (reference:
        fsm.go applyDeploymentStatusUpdate)."""
        return self._propose("deployment_status", {
            "updates": [to_wire(update)],
            "mark_stable": list(mark_stable) if mark_stable else None})

    def promote_deployment(self, dep_id: str,
                           all_groups: bool = True,
                           groups=None) -> Optional[Evaluation]:
        """Promote canaries (reference: deployments_watcher.go
        PromoteDeployment -> fsm applyDeploymentPromotion): flips the
        groups' promoted bit and evaluates the job so the reconciler
        replaces the old version."""
        dep = self.store.deployment_by_id(dep_id)
        if dep is None or not dep.active():
            return None
        # reference PromoteDeployment rejects unhealthy canaries — the
        # promotion replaces the known-good version cluster-wide
        unhealthy = self._unhealthy_canary_groups(
            dep, None if all_groups else groups)
        if unhealthy:
            raise ValueError(
                f"canaries not healthy in group(s): {', '.join(unhealthy)}")
        self._propose("deployment_promote", {
            "dep_id": dep_id, "groups": None if all_groups else groups})
        job = self.store.job_by_id(dep.namespace, dep.job_id)
        if job is None:
            return None
        ev = Evaluation(
            namespace=dep.namespace, job_id=dep.job_id, type=job.type,
            priority=job.priority, deployment_id=dep_id,
            triggered_by=EVAL_TRIGGER_DEPLOYMENT_PROMOTION,
            status=EVAL_STATUS_PENDING)
        self._create_evals([ev])
        return ev

    def _unhealthy_canary_groups(self, dep, groups=None) -> List[str]:
        out = []
        for name, state in dep.task_groups.items():
            if state.desired_canaries <= 0 or state.promoted:
                continue
            if groups is not None and name not in groups:
                continue
            healthy = 0
            for aid in state.placed_canaries:
                a = self.store.alloc_by_id(aid)
                if (a is not None and a.deployment_status is not None
                        and a.deployment_status.is_healthy()):
                    healthy += 1
            if healthy < state.desired_canaries:
                out.append(name)
        return out

    def fail_deployment(self, dep_id: str) -> Optional[Evaluation]:
        """Manual fail (reference: Deployment.Fail RPC)."""
        from ..structs import (DEPLOYMENT_STATUS_FAILED,
                               DeploymentStatusUpdate)
        dep = self.store.deployment_by_id(dep_id)
        if dep is None or not dep.active():
            return None
        self.apply_deployment_status_update(DeploymentStatusUpdate(
            deployment_id=dep_id, status=DEPLOYMENT_STATUS_FAILED,
            status_description="Deployment marked as failed"))
        job = self.store.job_by_id(dep.namespace, dep.job_id)
        if job is None:
            return None
        ev = Evaluation(
            namespace=dep.namespace, job_id=dep.job_id, type=job.type,
            priority=job.priority, deployment_id=dep_id,
            triggered_by=EVAL_TRIGGER_DEPLOYMENT_WATCHER,
            status=EVAL_STATUS_PENDING)
        self._create_evals([ev])
        return ev

    def revert_job(self, stable_job: Job) -> Optional[Evaluation]:
        """Re-register a historical job version as the newest one
        (reference: Job.Revert — copies the old version forward)."""
        import copy as _copy
        j = _copy.deepcopy(stable_job)
        j.create_index = j.modify_index = j.job_modify_index = 0
        return self.register_job(j)

    def revert_job_version(self, namespace: str, job_id: str,
                           version: int,
                           enforce_prior_version: Optional[int] = None
                           ) -> Tuple[int, Optional[Evaluation]]:
        """Manual revert to a retained version (reference:
        nomad/job_endpoint.go Job.Revert — validates the target exists,
        optionally CAS-checks the current version, then registers the
        old version forward as a NEW version)."""
        cur = self.store.job_by_id(namespace, job_id)
        if cur is None:
            raise ValueError(f"unknown job {job_id!r}")
        if enforce_prior_version is not None \
                and cur.version != enforce_prior_version:
            raise ValueError(
                f"current version is {cur.version}, "
                f"not {enforce_prior_version}")
        if version == cur.version:
            raise ValueError(
                f"cannot revert to the current version ({version})")
        target = self.store.job_by_id_and_version(namespace, job_id,
                                                  version)
        if target is None:
            raise ValueError(f"job {job_id!r} has no version {version}")
        ev = self.revert_job(target)
        new = self.store.job_by_id(namespace, job_id)
        return (new.version if new else 0), ev

    def set_job_stability(self, namespace: str, job_id: str,
                          version: int, stable: bool) -> None:
        """Manually mark a job version (un)stable (reference:
        Job.Stable — the auto-revert target set by hand)."""
        if self.store.job_by_id_and_version(namespace, job_id,
                                            version) is None:
            raise ValueError(f"job {job_id!r} has no version {version}")
        self._propose("job_stability", {
            "namespace": namespace, "job_id": job_id,
            "version": version, "stable": bool(stable)})

    # reference: structs.DispatchPayloadSizeLimit (16 KiB)
    DISPATCH_PAYLOAD_LIMIT = 16 * 1024

    def dispatch_job(self, namespace: str, job_id: str,
                     payload: bytes = b"",
                     meta: Optional[Dict[str, str]] = None
                     ) -> Tuple[Job, Optional[Evaluation]]:
        """Instantiate a parameterized job (reference:
        nomad/job_endpoint.go Job.Dispatch): validate payload presence
        against the template's constraint and the dispatch meta against
        the declared keys, then register a child carrying the payload
        (delivered to the task dir by the task runner's
        dispatch_payload hook)."""
        import copy as _copy
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            raise ValueError(f"unknown job {job_id!r}")
        if not job.is_parameterized():
            raise ValueError(f"job {job_id!r} is not parameterized")
        cfg = job.parameterized
        payload = bytes(payload or b"")
        if cfg.payload == "required" and not payload:
            raise ValueError("job requires a dispatch payload")
        if cfg.payload == "forbidden" and payload:
            raise ValueError("job forbids a dispatch payload")
        if len(payload) > self.DISPATCH_PAYLOAD_LIMIT:
            raise ValueError(
                f"payload exceeds {self.DISPATCH_PAYLOAD_LIMIT} bytes")
        meta = dict(meta or {})
        missing = [k for k in cfg.meta_required if k not in meta]
        if missing:
            raise ValueError(f"missing required dispatch meta: "
                             f"{sorted(missing)}")
        allowed = set(cfg.meta_required) | set(cfg.meta_optional)
        extra = [k for k in meta if k not in allowed]
        if extra:
            raise ValueError(f"dispatch meta keys not declared by the "
                             f"job: {sorted(extra)}")
        child = _copy.deepcopy(job)
        child.id = (f"{job.id}/dispatch-{int(_time.time())}-"
                    f"{generate_uuid()[:8]}")
        child.name = child.id
        child.parent_id = job.id
        child.dispatched = True
        child.payload = payload
        child.meta = {**(job.meta or {}), **meta}
        child.create_index = child.modify_index = 0
        child.job_modify_index = 0
        ev = self.register_job(child)
        stored = self.store.job_by_id(namespace, child.id) or child
        return stored, ev

    # --------------------------------------------------- raft membership
    def add_server_peer(self, peer_id: str, addr=None,
                        catchup_timeout_s: float = 10.0) -> int:
        """One-at-a-time raft membership add (reference: raft
        AddVoter via nomad/leader.go addRaftPeer on serf join). The new
        server first replicates as a NON-VOTER until it holds the
        leader's committed log (the learner phase), then joins the
        voting config — so a lagging joiner never drags quorum. `addr`
        updates the transport's peer map when it routes by address."""
        if addr is not None and hasattr(self.raft.transport,
                                        "peer_addrs"):
            self.raft.transport.peer_addrs[peer_id] = addr
        peers = list(self.raft.cfg.peers)
        if peer_id in peers:
            return self.store.latest_index()
        self.raft.add_learner(peer_id)
        try:
            deadline = _time.monotonic() + catchup_timeout_s
            while not self.raft.learner_caught_up(peer_id):
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"peer {peer_id} did not catch up in "
                        f"{catchup_timeout_s}s")
                if not self.is_leader():
                    raise NotLeaderError(self.raft.leader_id)
                _time.sleep(0.02)
            # re-read the config: another membership change may have
            # committed during the catch-up wait
            peers = list(self.raft.cfg.peers)
            if peer_id in peers:
                return self.store.latest_index()
            return self.raft.propose_config(peers + [peer_id])
        finally:
            self.raft.remove_learner(peer_id)

    def remove_server_peer(self, peer_id: str) -> int:
        """Membership removal (reference: removeRaftPeer; autopilot's
        dead-server cleanup calls this when gossip marks a server
        failed)."""
        peers = [p for p in self.raft.cfg.peers if p != peer_id]
        if len(peers) == len(self.raft.cfg.peers):
            return self.store.latest_index()
        return self.raft.propose_config(peers)

    def attach_gossip(self, gossip) -> None:
        """Autopilot wiring (reference: nomad/autopilot.go dead-server
        cleanup + serf.go nodeFailed -> removeRaftPeer): when gossip
        declares a SERVER member dead, the leader removes it from the
        raft peer set so quorum shrinks to the live members. The
        edge-triggered on_fail is backed by a periodic leader-side
        reconcile (the reference reconciles from the leader loop), so a
        death that fires while no stable leader exists is still cleaned
        up."""
        self.gossip = gossip
        prev = gossip.on_fail

        def on_fail(member):
            if prev is not None:
                prev(member)
            self._autopilot_reconcile()
        gossip.on_fail = on_fail

    def _autopilot_reconcile(self) -> None:
        gossip = getattr(self, "gossip", None)
        if gossip is None or not self.is_leader():
            return
        from ..membership.gossip import STATUS_DEAD, STATUS_LEFT
        for peer in list(self.raft.cfg.peers):
            m = gossip.member(peer)
            if m is not None and m.status in (STATUS_DEAD, STATUS_LEFT):
                try:
                    self.remove_server_peer(peer)
                except (ValueError, Exception) as e:  # noqa: BLE001
                    import logging
                    logging.getLogger(__name__).info(
                        "autopilot: removal of %s deferred: %s",
                        peer, e)
                return    # one at a time; the next tick continues

    # ------------------------------------------------------------ secrets
    def upsert_secret(self, namespace: str, path: str,
                      data: Dict[str, str]) -> int:
        """Native secret KV write (the Vault-analog store; raft-
        replicated like every other table)."""
        return self._propose("secret_upsert", {
            "namespace": namespace, "path": path, "data": dict(data)})

    def delete_secret(self, namespace: str, path: str) -> int:
        return self._propose("secret_delete",
                             {"namespace": namespace, "path": path})

    # --------------------------------------------------------------- ACL
    def bootstrap_acl(self):
        """One-time creation of the initial management token
        (reference: acl_endpoint.go Bootstrap)."""
        from ..acl import ACLToken
        if self.store.acl_bootstrapped():
            # the flag persists even if every management token is later
            # deleted — a re-opened anonymous bootstrap would be a
            # privilege escalation (reference: the raft-persisted
            # bootstrap index, acl_endpoint.go Bootstrap)
            raise ValueError("ACL already bootstrapped")
        token = ACLToken(accessor_id=generate_uuid(),
                         secret_id=generate_uuid(),
                         name="Bootstrap Token", type="management",
                         global_=True)
        self._propose("acl_token_upsert", {"token": to_wire(token),
                                           "bootstrap": True})
        return token

    def upsert_acl_policy(self, policy) -> int:
        return self._propose("acl_policy_upsert",
                             {"policy": to_wire(policy)})

    def delete_acl_policy(self, name: str) -> int:
        return self._propose("acl_policy_delete", {"name": name})

    def upsert_acl_token(self, token) -> int:
        if not token.accessor_id:
            token.accessor_id = generate_uuid()
        if not token.secret_id:
            token.secret_id = generate_uuid()
        return self._propose("acl_token_upsert",
                             {"token": to_wire(token)})

    def delete_acl_token(self, accessor_id: str) -> int:
        return self._propose("acl_token_delete",
                             {"accessor_id": accessor_id})

    def resolve_token(self, secret_id: str):
        """Secret -> compiled ACL (reference: nomad/acl.go ResolveToken;
        the reference caches compiled ACLs in an LRU — policy sets here
        are small enough to compile per call)."""
        from ..acl import compile_acl, management_acl
        token = self.store.acl_token_by_secret(secret_id)
        if token is None:
            return None
        if token.is_management():
            return management_acl()
        policies = [p for p in (self.store.acl_policy_by_name(n)
                                for n in token.policies) if p is not None]
        return compile_acl(policies)

    # -------------------------------------------------------- CSI volumes
    def register_csi_volume(self, vol) -> int:
        """CSIVolume.Register analog (nomad/csi_endpoint.go)."""
        return self._propose("csi_volume_upsert", {"volume": to_wire(vol)})

    def deregister_csi_volume(self, namespace: str, vol_id: str) -> int:
        vol = self.store.csi_volume_by_id(namespace, vol_id)
        if vol is not None and vol.in_use():
            raise ValueError(f"volume {vol_id} is in use")
        return self._propose("csi_volume_delete",
                             {"namespace": namespace, "volume_id": vol_id})

    def claim_csi_volume(self, namespace: str, vol_id: str, mode: str,
                         alloc_id: str, node_id: str) -> int:
        """CSIVolume.Claim analog: validated here (the plan applier is
        the serialization point for placements), applied via raft."""
        vol = self.store.csi_volume_by_id(namespace, vol_id)
        if vol is None:
            raise KeyError(f"volume {vol_id} not found")
        from ..structs import CLAIM_WRITE
        if mode == CLAIM_WRITE and not vol.write_free() \
                and alloc_id not in vol.write_claims:
            raise ValueError(f"volume {vol_id} has no free write claims")
        return self._propose("csi_volume_claim", {
            "namespace": namespace, "volume_id": vol_id, "mode": mode,
            "alloc_id": alloc_id, "node_id": node_id})

    def release_csi_claims(self, alloc_id: str) -> int:
        return self._propose("csi_claims_release", {"alloc_id": alloc_id})

    # ----------------------------------------------------------- GC reaps
    def reap_evals(self, eval_ids: List[str], alloc_ids: List[str]) -> int:
        """Eval.Reap analog: delete evals + allocs in one apply."""
        return self._propose("evals_reap", {"eval_ids": list(eval_ids),
                                            "alloc_ids": list(alloc_ids)})

    def reap_jobs(self, keys: List) -> int:
        """Job.BatchDeregister(purge) analog; keys = (namespace, id)."""
        return self._propose("jobs_reap",
                             {"keys": [list(k) for k in keys]})

    def reap_nodes(self, node_ids: List[str]) -> int:
        index = self._propose("nodes_reap", {"node_ids": list(node_ids)})
        for nid in node_ids:
            self.heartbeater.clear(nid)
        return index

    def reap_deployments(self, dep_ids: List[str]) -> int:
        return self._propose("deployments_reap",
                             {"dep_ids": list(dep_ids)})

    def record_periodic_launch(self, namespace: str, job_id: str,
                               launch: float) -> int:
        return self._propose("periodic_launch", {
            "namespace": namespace, "job_id": job_id, "launch": launch})

    # ------------------------------------------------------- plan applier
    def alloc_migrate_source(self, alloc_id: str):
        """Ephemeral-disk migration source info for a previous alloc
        (reference: Node.GetClientAllocs attaches MigrateTokens —
        structs.GenerateMigrateToken under the OWNING node's secret, so
        that agent verifies reads without a server round trip)."""
        from ..structs.funcs import generate_migrate_token
        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            return None
        node = self.store.node_by_id(alloc.node_id)
        if node is None:
            # the owning node is gone: nothing to stream from, and a
            # token minted under an empty secret would be forgeable
            return None
        return {
            "alloc_id": alloc_id,
            "namespace": alloc.namespace,
            # CLIENT-terminal: the old tasks must have actually stopped
            # writing before the data is copied (reference: allocwatcher
            # waits for client-terminal, not desired-stop)
            "terminal": alloc.client_terminal_status(),
            "node_id": alloc.node_id,
            "addr": node.attributes.get("unique.advertise.http", ""),
            "migrate_token": generate_migrate_token(alloc_id,
                                                    node.secret_id),
        }

    def _apply_plan(self, plan: Plan, result: PlanResult) -> int:
        index = self._propose("plan_result", {
            "result": to_wire(result),
            "job": to_wire(plan.job) if plan.job is not None else None})
        self._claim_csi_for_placements(plan, result)
        return index

    def _apply_plan_async(self, plan: Plan, result: PlanResult):
        """Dispatch the plan's raft apply without waiting; returns
        (index, finish_fn) — finish_fn blocks until the entry is
        applied and then claims CSI volumes.  The applier pipelines
        plan N+1's evaluation under plan N's consensus round trip."""
        index, wait = self.raft.propose_async("plan_result", {
            "result": to_wire(result),
            "job": to_wire(plan.job) if plan.job is not None else None})

        def finish(timeout: float = 10.0) -> int:
            ix = wait(timeout)
            self._claim_csi_for_placements(plan, result)
            return ix
        return index, finish

    def _apply_plan_batch_async(self, items):
        """Group commit (ISSUE 17): K plan results ride ONE raft entry —
        one log append, one fsync — instead of K.  `items` is
        [(plan, result)]; returns (index, finish_fn) like the single
        path.  The FSM applies the K results in submission order under
        the shared commit index, which is the same store state K chained
        single applies would produce."""
        index, wait = self.raft.propose_async("plan_results_batch", {
            "items": [{
                "result": to_wire(result),
                "job": to_wire(plan.job) if plan.job is not None else None,
            } for plan, result in items]})

        def finish(timeout: float = 10.0) -> int:
            ix = wait(timeout)
            for plan, result in items:
                self._claim_csi_for_placements(plan, result)
            return ix
        return index, finish

    def _claim_csi_for_placements(self, plan: Plan,
                                  result: PlanResult) -> None:
        """Claim CSI volumes for newly committed placements (reference:
        the csi_hook's Volume.Claim at alloc start; here the serial plan
        applier is the claim serialization point, so the scheduler's
        write-capacity gate and this claim see consistent state)."""
        from ..structs import CLAIM_READ, CLAIM_WRITE
        job = plan.job
        if job is None:
            return
        tgs = {tg.name: tg for tg in job.task_groups}
        for allocs in result.node_allocation.values():
            for a in allocs:
                tg = tgs.get(a.task_group)
                if tg is None:
                    continue
                for req in tg.volumes.values():
                    if req.type != "csi":
                        continue
                    mode = CLAIM_READ if req.read_only else CLAIM_WRITE
                    try:
                        self.claim_csi_volume(job.namespace, req.source,
                                              mode, a.id, a.node_id)
                    except (KeyError, ValueError):
                        import logging
                        logging.getLogger(__name__).warning(
                            "csi claim failed for alloc %s volume %s",
                            a.id, req.source)
