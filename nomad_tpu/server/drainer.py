"""Leader-side node drainer (reference: nomad/drainer/ — NodeDrainer
drainer.go:130, deadline heap drain_heap.go, per-job pacing
watch_jobs.go, node watcher watch_nodes.go).

Draining never stops allocs directly: it marks them
DesiredTransition{migrate} in paced waves — at most the migrate stanza's
max_parallel in flight per task group — and lets the scheduler replace
them. System allocs drain only after every non-system alloc is gone
(unless ignore_system_jobs). At the drain deadline every remaining alloc
is force-migrated. When nothing drainable remains the node's drain is
cleared, leaving it ineligible.
"""
from __future__ import annotations

import logging
import threading
import time as _time
from typing import Dict, List, Optional, Tuple

from ..structs import (ALLOC_CLIENT_PENDING, ALLOC_CLIENT_RUNNING,
                       EVAL_STATUS_PENDING, EVAL_TRIGGER_NODE_DRAIN,
                       Allocation, Evaluation, Node)

_log = logging.getLogger(__name__)

DEFAULT_MAX_PARALLEL = 1


class NodeDrainer:
    def __init__(self, server, poll_interval_s: float = 0.05):
        self.server = server
        self.poll_interval_s = poll_interval_s
        self._enabled = False
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None

    def set_enabled(self, enabled: bool) -> None:
        thread = None
        with self._cv:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            if enabled:
                # thread handle guarded by _cv (nomadlint LOCK301)
                self._thread = threading.Thread(target=self._watch,
                                                daemon=True)
                self._thread.start()
            else:
                thread, self._thread = self._thread, None
                self._cv.notify_all()
        if thread is not None:
            thread.join(timeout=1.0)

    # --------------------------------------------------------------- loop
    def _watch(self) -> None:
        store = self.server.store
        while True:
            with self._cv:
                if not self._enabled:
                    return
            try:
                for node in list(store.nodes()):
                    if node.drain_strategy is not None:
                        self._drain_node(node)
            except Exception:
                _log.exception("drainer pass failed")
            store.wait_for_change(store.latest_index(),
                                  self.poll_interval_s * 4)

    # -------------------------------------------------------------- drain
    def _drain_node(self, node: Node) -> None:
        strategy = node.drain_strategy
        now = _time.time()
        allocs = [a for a in self.server.store.allocs_by_node(node.id)
                  if not a.terminal_status()]
        system, services = [], []
        for a in allocs:
            (system if a.job is not None and a.job.is_system()
             else services).append(a)

        force = (strategy.force_deadline > 0
                 and now >= strategy.force_deadline) \
            or strategy.deadline_s < 0          # -1: drain immediately

        if force:
            # deadline hit: everything remaining migrates NOW
            # (reference: drain_heap expiry -> watch_nodes force path)
            remaining = services + ([] if strategy.ignore_system_jobs
                                    else system)
            to_mark = [a for a in remaining
                       if not a.desired_transition.should_migrate()]
            if to_mark:
                self.server.drain_allocs([a.id for a in to_mark])
            if not remaining:
                self._finish(node)
            return

        if not services:
            # non-system work done: drain system allocs, then finish
            drainable_system = [] if strategy.ignore_system_jobs else system
            to_mark = [a for a in drainable_system
                       if not a.desired_transition.should_migrate()]
            if to_mark:
                self.server.drain_allocs([a.id for a in to_mark])
            if not drainable_system:
                self._finish(node)
            return

        # paced waves per (job, task group) honoring the migrate stanza;
        # batch allocs are never marked — they may run to the deadline
        # (reference: watch_jobs.go:333-335,401)
        by_tg: Dict[Tuple[str, str, str], List[Allocation]] = {}
        for a in services:
            if a.job is not None and a.job.is_batch():
                continue
            by_tg.setdefault((a.namespace, a.job_id, a.task_group),
                             []).append(a)
        mark: List[str] = []
        for (ns, job_id, tg_name), group_allocs in by_tg.items():
            job = group_allocs[0].job or \
                self.server.store.job_by_id(ns, job_id)
            tg = job.lookup_task_group(tg_name) if job else None
            max_parallel = (tg.migrate.max_parallel
                            if tg is not None and tg.migrate is not None
                            else DEFAULT_MAX_PARALLEL)
            count = tg.count if tg is not None else len(group_allocs)
            # reference pacing (watch_jobs.go:405-411):
            #   numToDrain = healthy - (count - max_parallel)
            healthy = self._healthy(ns, job_id, tg_name)
            allowed = min(
                healthy - (count - max_parallel),
                len([a for a in group_allocs
                     if not a.desired_transition.should_migrate()]))
            if allowed <= 0:
                continue
            candidates = [a for a in group_allocs
                          if not a.desired_transition.should_migrate()]
            mark.extend(a.id for a in candidates[:allowed])
        if mark:
            self.server.drain_allocs(mark)

    def _healthy(self, ns: str, job_id: str, tg_name: str) -> int:
        """Healthy-from-a-migration-standpoint count (reference:
        watch_jobs.go:371-375): non-terminal allocs whose health is
        reported, falling back to client_status running when no health
        tracking applies."""
        count = 0
        for a in self.server.store.allocs_by_job(ns, job_id):
            if a.task_group != tg_name or a.terminal_status():
                continue
            # an alloc already marked for migration is capacity in flight,
            # not stable capacity — counting it would let the next pass
            # mark a second wave before the first one even stops
            if a.desired_transition.should_migrate():
                continue
            if a.deployment_status is not None \
                    and a.deployment_status.healthy is not None:
                if a.deployment_status.is_healthy():
                    count += 1
            elif a.client_status == ALLOC_CLIENT_RUNNING:
                count += 1
        return count

    def _finish(self, node: Node) -> None:
        """Drain complete: clear the strategy, keep the node ineligible
        (reference: watch_nodes.go handleDoneNodes)."""
        self.server.update_node_drain(node.id, None, mark_eligible=False)
        _log.info("node %s drain complete", node.id[:8])
