"""BlockedEvals: evals that failed placement, waiting for capacity.

Reference: nomad/blocked_evals.go — Block :166, class/quota-keyed Unblock
:418, UnblockNode :501, missed-unblock index check :316, per-job dedup
with duplicate surfacing :642.

Extension (ISSUE 6, serving tier): a `shed` lane for evals the
admission controller refused at ingress under overload.  Shed evals are
never dropped — they share the per-job dedup/duplicate machinery with
capacity-blocked evals and are popped back into the broker in priority
order by `pop_shed` once the queue drains (the worker's readmit tick).
Unlike capacity-blocked evals they do NOT unblock on capacity change:
they wait on queue drain, not on node state.
"""
from __future__ import annotations

import heapq
import itertools
import threading
from typing import Dict, List, Tuple

from ..structs import EVAL_STATUS_PENDING, Evaluation


class BlockedEvals:
    def __init__(self, broker):
        self._lock = threading.Lock()
        self._broker = broker
        self._enabled = False
        self._captured: Dict[str, Evaluation] = {}
        self._escaped: Dict[str, Evaluation] = {}
        self._by_job: Dict[Tuple[str, str], str] = {}
        self._by_node: Dict[str, List[str]] = {}   # system evals per node
        self._node_of: Dict[str, str] = {}         # eval id -> node id
        self._duplicates: List[Evaluation] = []
        self._dup_event = threading.Event()
        # class -> latest state index at which capacity changed; an eval
        # blocked with an older snapshot may have missed that unblock
        self._unblock_indexes: Dict[str, int] = {}
        # admission-shed evals (ISSUE 6): id -> eval plus a max-priority
        # pop order; total_shed counts lifetime sheds for the stats line
        self._shed: Dict[str, Evaluation] = {}
        self._shed_heap: List[tuple] = []
        self._shed_count = itertools.count()
        self._sheds_total = 0

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._captured.clear()
                self._escaped.clear()
                self._by_job.clear()
                self._by_node.clear()
                self._duplicates.clear()
                self._unblock_indexes.clear()
                self._shed.clear()
                self._shed_heap.clear()

    @property
    def enabled(self) -> bool:
        with self._lock:    # guarded by _lock: see set_enabled
            return self._enabled

    # --------------------------------------------------------------- block
    def block(self, ev: Evaluation) -> None:
        with self._lock:
            if not self._enabled:
                return
            if (ev.id in self._captured or ev.id in self._escaped
                    or ev.id in self._shed):
                return
            namespaced = (ev.namespace, ev.job_id)
            existing_id = self._by_job.get(namespaced)
            if existing_id is not None and existing_id != ev.id:
                # one blocked eval per job: newer wins, older surfaces as a
                # duplicate for cancellation
                old = self._captured.pop(existing_id, None) \
                    or self._escaped.pop(existing_id, None) \
                    or self._shed.pop(existing_id, None)
                if old is not None:
                    self._scrub_node_locked(existing_id)
                    self._duplicates.append(old)
                    self._dup_event.set()
            self._by_job[namespaced] = ev.id

            # missed-unblock check: capacity may have changed between the
            # scheduler's snapshot and now
            if self._missed_unblock_locked(ev):
                self._by_job.pop(namespaced, None)
                self._broker.enqueue(_reset(ev))
                return

            if ev.escaped_computed_class or not ev.class_eligibility:
                self._escaped[ev.id] = ev
            else:
                self._captured[ev.id] = ev
            if ev.node_id:
                self._by_node.setdefault(ev.node_id, []).append(ev.id)
                self._node_of[ev.id] = ev.node_id

    def _missed_unblock_locked(self, ev: Evaluation) -> bool:
        if not ev.snapshot_index:
            return False
        for cls, index in self._unblock_indexes.items():
            if index <= ev.snapshot_index:
                continue
            elig = ev.class_eligibility.get(cls)
            if elig is None or elig:
                # unseen or eligible class changed after our snapshot
                return True
            if ev.escaped_computed_class:
                return True
        return False

    # ---------------------------------------------------------------- shed
    def shed(self, ev: Evaluation) -> None:
        """Park an admission-shed eval (serving tier backpressure).
        Same per-job dedup as block(): newer wins, the displaced eval
        surfaces as a duplicate for cancellation — shedding never
        silently drops work."""
        with self._lock:
            if not self._enabled:
                return
            if (ev.id in self._shed or ev.id in self._captured
                    or ev.id in self._escaped):
                return
            namespaced = (ev.namespace, ev.job_id)
            existing_id = self._by_job.get(namespaced)
            if existing_id is not None and existing_id != ev.id:
                old = self._captured.pop(existing_id, None) \
                    or self._escaped.pop(existing_id, None) \
                    or self._shed.pop(existing_id, None)
                if old is not None:
                    self._scrub_node_locked(existing_id)
                    self._duplicates.append(old)
                    self._dup_event.set()
            if ev.job_id:
                self._by_job[namespaced] = ev.id
            self._shed[ev.id] = ev
            heapq.heappush(self._shed_heap,
                           (-ev.priority, next(self._shed_count), ev.id))
            self._sheds_total += 1

    def pop_shed(self, max_n: int) -> List[Evaluation]:
        """Pop up to max_n shed evals in (priority desc, shed order)
        for readmission; the caller re-enqueues them on the broker.
        Stale heap entries (displaced by a newer eval for the job) are
        skipped — the newer eval owns the job slot."""
        out: List[Evaluation] = []
        with self._lock:
            while self._shed_heap and len(out) < max_n:
                _, _, eid = heapq.heappop(self._shed_heap)
                ev = self._shed.pop(eid, None)
                if ev is None:
                    continue
                self._by_job.pop((ev.namespace, ev.job_id), None)
                out.append(ev)
        return [_reset(ev) for ev in out]

    def shed_count(self) -> int:
        with self._lock:
            return len(self._shed)

    # ------------------------------------------------------------- unblock
    def unblock(self, computed_class: str, index: int) -> None:
        """Capacity changed on nodes of `computed_class` at state `index`."""
        with self._lock:
            if not self._enabled:
                return
            self._unblock_indexes[computed_class] = index
            unblock: List[Evaluation] = []
            for eid, ev in list(self._escaped.items()):
                unblock.append(ev)
                del self._escaped[eid]
            for eid, ev in list(self._captured.items()):
                elig = ev.class_eligibility.get(computed_class)
                if elig is None or elig:
                    unblock.append(ev)
                    del self._captured[eid]
            for ev in unblock:
                self._by_job.pop((ev.namespace, ev.job_id), None)
                self._scrub_node_locked(ev.id)
        for ev in unblock:
            self._broker.enqueue(_reset(ev))

    def unblock_all(self, index: int) -> None:
        with self._lock:
            if not self._enabled:
                return
            evs = list(self._captured.values()) + list(self._escaped.values())
            self._captured.clear()
            self._escaped.clear()
            self._by_job.clear()
            self._by_node.clear()
            self._node_of.clear()
        for ev in evs:
            self._broker.enqueue(_reset(ev))

    def unblock_node(self, node_id: str, index: int) -> None:
        with self._lock:
            if not self._enabled:
                return
            ids = self._by_node.pop(node_id, [])
            evs = []
            for eid in ids:
                self._node_of.pop(eid, None)
                ev = self._captured.pop(eid, None) \
                    or self._escaped.pop(eid, None)
                if ev is not None:
                    self._by_job.pop((ev.namespace, ev.job_id), None)
                    evs.append(ev)
        for ev in evs:
            self._broker.enqueue(_reset(ev))

    # ------------------------------------------------------------ plumbing
    def untrack(self, namespace: str, job_id: str) -> None:
        """Job deregistered: drop its blocked eval."""
        with self._lock:
            eid = self._by_job.pop((namespace, job_id), None)
            if eid:
                self._captured.pop(eid, None)
                self._escaped.pop(eid, None)
                self._shed.pop(eid, None)
                self._scrub_node_locked(eid)

    def _scrub_node_locked(self, eval_id: str) -> None:
        nid = self._node_of.pop(eval_id, None)
        if nid is None:
            return
        ids = self._by_node.get(nid)
        if ids:
            ids = [i for i in ids if i != eval_id]
            if ids:
                self._by_node[nid] = ids
            else:
                del self._by_node[nid]

    def get_duplicates(self, timeout: float = 0.0) -> List[Evaluation]:
        if timeout:
            self._dup_event.wait(timeout)
        with self._lock:
            dups = self._duplicates
            self._duplicates = []
            self._dup_event.clear()
            return dups

    def stats(self) -> dict:
        with self._lock:
            return {
                "total_blocked": len(self._captured),
                "total_escaped": len(self._escaped),
                "total_shed": len(self._shed),
                "sheds_lifetime": self._sheds_total,
            }


def _reset(ev: Evaluation) -> Evaluation:
    import copy
    e = copy.copy(ev)
    e.status = EVAL_STATUS_PENDING
    e.status_description = ""
    return e
