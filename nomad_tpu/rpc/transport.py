"""Raft peer transport over the RPC substrate.

Reference: nomad/raft_rpc.go — raft gets its own stream family on the
shared listener. Here the raft verbs register as `raft.*` methods on
the server's RpcServer, and `call` dials peers through pooled clients.
Implements the same surface as raft.node.InProcTransport, so RaftNode
is transport-agnostic.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Tuple

from ..utils.codec import from_wire, to_wire
from .client import ClientPool, RpcError
from .server import RpcHandlerError, RpcServer

_log = logging.getLogger(__name__)

# raft verbs must fail FAST on dead peers: the replication loop is
# sequential and the election timeout is 150-300ms, so a blocking dial
# would destabilize the healthy majority. A failed peer backs off
# exponentially (capped) before the next dial attempt.
RAFT_CALL_TIMEOUT_S = 2.0
BACKOFF_BASE_S = 0.25
BACKOFF_MAX_S = 5.0
VOTE_PROBE_TIMEOUT_S = 1.0
# the exempt-probe window must cover at least one full blocked dial,
# or a black-holed peer gets a fresh blocking probe every election
# round (each round is naturally spaced by the dial timeout itself)
VOTE_PROBE_WINDOW_S = 2 * VOTE_PROBE_TIMEOUT_S


class TcpRaftTransport:
    def __init__(self, rpc_server: RpcServer,
                 peer_addrs: Dict[str, Tuple[str, int]], tls=None,
                 verify_hostname: str = ""):
        """peer_addrs: raft node id -> (host, port) of that peer's
        RpcServer (including this node's own).  `tls`: client-side
        ssl context for peer dials (mutual TLS); `verify_hostname`
        additionally pins the dialed peer's SAN role (raft peers must
        present server.<region>.nomad)."""
        self.rpc_server = rpc_server
        self.peer_addrs = dict(peer_addrs)
        self._pool = ClientPool(tls=tls, verify_hostname=verify_hostname)
        self._lock = threading.Lock()
        self._local: Dict[str, Any] = {}
        self._backoff: Dict[str, Tuple[float, int]] = {}  # until, fails
        self._vote_probe: Dict[str, float] = {}  # last exempt vote dial

    # -- the InProcTransport surface ----------------------------------
    def register(self, node) -> None:
        self._local[node.id] = node

        def handler(params, _v, _n):
            # the InProcTransport contract: a stopped (or replaced) node
            # is unreachable — it must not vote or ACK appends, or a
            # leader could count a non-durable ACK toward majority
            if not _n.running or self._local.get(_n.id) is not _n:
                raise RpcHandlerError("unreachable",
                                      f"raft node {_n.id} not running")
            return _to_jsonable(getattr(_n, _v)(*_decode_args(_v, params)))

        for verb in ("rpc_request_vote", "rpc_append_entries",
                     "rpc_install_snapshot"):
            # raft is strictly server-to-server: with mTLS on, a
            # client-role cert must not be able to vote or append
            self.rpc_server.register(
                f"raft.{verb}",
                lambda params, _v=verb, _n=node: handler(params, _v, _n),
                server_only=True)

    def unregister(self, node_id: str) -> None:
        self._local.pop(node_id, None)

    def call(self, target: str, method: str, *args):
        local = self._local.get(target)
        if local is not None:
            if not local.running:
                raise ConnectionError(f"peer {target} unreachable")
            return getattr(local, method)(*args)
        addr = self.peer_addrs.get(target)
        if addr is None:
            raise ConnectionError(f"no address for peer {target}")
        now = time.monotonic()
        with self._lock:
            until, fails = self._backoff.get(target, (0.0, 0))
            if now < until:
                # elections must still be able to reach a slow-but-
                # alive peer, but a black-holed peer must not reinstate
                # blocking dials in the sequential election loop: allow
                # ONE exempt vote probe per probe window (the window is
                # wider than the probe's own dial timeout, so at most
                # half of any period can be spent blocked on one peer)
                if method != "rpc_request_vote":
                    raise ConnectionError(f"peer {target} backing off")
                last = self._vote_probe.get(target, 0.0)
                if now - last < VOTE_PROBE_WINDOW_S:
                    raise ConnectionError(f"peer {target} backing off")
                self._vote_probe[target] = now
        client = self._pool.get(target, addr)
        try:
            out = client.call(f"raft.{method}",
                              _encode_args(method, list(args)),
                              timeout=(VOTE_PROBE_TIMEOUT_S
                                       if method == "rpc_request_vote"
                                       else RAFT_CALL_TIMEOUT_S))
        except RpcError as e:
            raise ConnectionError(f"peer {target}: {e}") from e
        except ValueError as e:
            # oversized frame (giant snapshot): every retry will fail the
            # same way — make the wedge loud instead of silent
            _log.error("raft %s to %s exceeds the frame limit: %s",
                       method, target, e)
            raise ConnectionError(f"peer {target}: {e}") from e
        except ConnectionError:
            with self._lock:
                _until, fails = self._backoff.get(target, (0.0, 0))
                delay = min(BACKOFF_BASE_S * (2 ** fails), BACKOFF_MAX_S)
                self._backoff[target] = (time.monotonic() + delay,
                                         fails + 1)
            raise
        with self._lock:
            self._backoff.pop(target, None)
        return _decode_result(method, out)


# bytes (snapshot payloads) ride the codec's base64 envelope; everything
# else in the raft verbs is already JSON-able (entries are tuples of
# JSON payloads)
def _encode_args(method: str, args):
    return [to_wire(a) if isinstance(a, bytes) else a for a in args]


def _decode_args(method: str, params):
    return [from_wire(bytes, p)
            if isinstance(p, dict) and "__b64__" in p else p
            for p in params]


def _to_jsonable(result):
    if isinstance(result, tuple):
        return list(result)
    return result


def _decode_result(method: str, out):
    # callers unpack fixed-arity tuples
    if isinstance(out, list):
        return tuple(out)
    return out
