"""Framing: 4-byte big-endian length + JSON body.

The reference multiplexes msgpack-RPC streams over yamux
(nomad/rpc.go:104); here each pooled connection carries one in-flight
request, so plain length-prefixed frames suffice and stay debuggable.
"""
from __future__ import annotations

import json
import socket
import struct
from typing import Any

MAX_FRAME = 64 * 1024 * 1024    # snapshots ship over this transport


def send_frame(sock: socket.socket, obj: Any) -> None:
    body = json.dumps(obj, separators=(",", ":")).encode()
    if len(body) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(body)}")
    sock.sendall(struct.pack(">I", len(body)) + body)


def recv_frame(sock: socket.socket) -> Any:
    header = _recv_exact(sock, 4)
    (n,) = struct.unpack(">I", header)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return json.loads(_recv_exact(sock, n).decode())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf.extend(chunk)
    return bytes(buf)
