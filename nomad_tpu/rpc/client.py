"""Pooled RPC client.

Reference: helper/pool ConnPool — persistent connections per server,
reused across requests. One in-flight request per pooled connection;
concurrent callers draw distinct sockets.
"""
from __future__ import annotations

import itertools
import os
import random
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .wire import recv_frame, send_frame

DIAL_TIMEOUT_S = 0.5
CALL_TIMEOUT_S = 30.0           # > blocking-query timeouts
# transient-transport retry policy (ISSUE 14): attempts beyond the
# first, capped jittered exponential backoff between them, all inside
# the per-call deadline (default: the call timeout, so existing
# callers' worst-case latency is unchanged)
MAX_RETRIES = int(os.environ.get("NOMAD_TPU_RPC_RETRIES", "2"))
RETRY_BASE_S = 0.02
RETRY_CAP_S = 0.25


class RpcError(Exception):
    def __init__(self, kind: str, message: str = "",
                 data: Optional[Dict[str, Any]] = None):
        super().__init__(f"{kind}: {message}" if message else kind)
        self.kind = kind
        self.message = message
        self.data = data or {}


class RpcClient:
    def __init__(self, addr: Tuple[str, int], pool_size: int = 4,
                 tls=None, verify_hostname: str = ""):
        """`tls`: an ssl.SSLContext from tlsutil.client_context —
        presents this node's cert and verifies the server against the
        cluster CA on every pooled dial.

        `verify_hostname`: expected SAN role of the PEER (e.g.
        "server.global.nomad") — applied post-handshake on every fresh
        dial (reference: VerifyServerHostname).  CA pinning alone
        accepts ANY cluster cert; the role check stops a client-role
        cert from impersonating a server."""
        self.addr = (addr[0], int(addr[1]))
        self._pool: List[socket.socket] = []
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._pool_size = pool_size
        self._tls = tls
        self._verify_hostname = verify_hostname

    def call(self, method: str, params: List[Any],
             timeout: float = CALL_TIMEOUT_S,
             retries: Optional[int] = None,
             deadline_s: Optional[float] = None) -> Any:
        """One request/response. Raises RpcError for typed application
        errors and ConnectionError for transport failures.

        Transient transport failures (dial refused, reset, torn frame)
        retry up to `retries` extra attempts with capped jittered
        exponential backoff, all inside one wall-clock deadline —
        `deadline_s` when given, else `timeout`, so a probe with
        timeout=0.5 still fails within ~0.5s total and liveness
        detection latency is unchanged.  Typed RpcErrors (the server
        answered) never retry."""
        retries = MAX_RETRIES if retries is None else int(retries)
        deadline = time.monotonic() + (
            timeout if deadline_s is None else deadline_s)
        attempt = 0
        while True:
            try:
                remaining = deadline - time.monotonic()
                if attempt and remaining <= 0:
                    raise ConnectionError(
                        f"rpc to {self.addr}: deadline exceeded after "
                        f"{attempt} attempt(s)")
                return self._call_once(method, params,
                                       min(timeout, max(remaining,
                                                        0.001)))
            except ConnectionError:
                from ..utils.metrics import global_metrics as _m
                attempt += 1
                if attempt > retries:
                    if attempt > 1:
                        _m.incr_counter("rpc.client.retries_exhausted")
                    raise
                delay = min(RETRY_CAP_S,
                            RETRY_BASE_S * (2 ** (attempt - 1)))
                delay *= 0.5 + random.random() / 2.0
                if time.monotonic() + delay >= deadline:
                    _m.incr_counter("rpc.client.deadline_exceeded")
                    raise
                _m.incr_counter("rpc.client.retries")
                time.sleep(delay)

    def _call_once(self, method: str, params: List[Any],
                   timeout: float) -> Any:
        from ..chaos.injection import global_injections
        inj = global_injections.get("rpc_transport")
        if inj is not None:
            inj.fire()
            raise ConnectionError(
                f"rpc to {self.addr}: injected transport fault")
        try:
            sock = self._checkout()
        except OSError as e:
            # dial/handshake failures (incl. TLS verification) present
            # uniformly as transport errors
            raise ConnectionError(f"rpc dial {self.addr}: {e}") from e
        try:
            sock.settimeout(timeout)
            send_frame(sock, {"id": next(self._ids), "method": method,
                              "params": params})
            resp = recv_frame(sock)
        except (OSError, ValueError) as e:
            try:
                sock.close()
            except OSError:
                pass
            raise ConnectionError(
                f"rpc to {self.addr}: {e}") from e
        self._checkin(sock)
        err = resp.get("error")
        if err is not None:
            raise RpcError(err.get("kind", "error"),
                           err.get("message", ""), err.get("data"))
        return resp.get("result")

    def close(self) -> None:
        with self._lock:
            for s in self._pool:
                try:
                    s.close()
                except OSError:
                    pass
            self._pool.clear()

    # ------------------------------------------------------------------
    def _checkout(self) -> socket.socket:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        sock = socket.create_connection(self.addr,
                                        timeout=DIAL_TIMEOUT_S)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._tls is not None:
            sock = self._tls.wrap_socket(
                sock, server_hostname=self.addr[0])
            if self._verify_hostname:
                from ..utils.tlsutil import peer_role
                role = peer_role(sock)
                if role != self._verify_hostname:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    raise OSError(
                        f"peer presented role {role!r}, expected "
                        f"{self._verify_hostname!r}")
        return sock

    def _checkin(self, sock: socket.socket) -> None:
        with self._lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass


class ClientPool:
    """Keyed RpcClient pool shared by the raft transport and the server
    endpoints; replacing a key's address closes the old client."""

    def __init__(self, tls=None, verify_hostname: str = ""):
        self._clients: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        self._tls = tls
        self._verify_hostname = verify_hostname

    def get(self, key: str, addr: Tuple[str, int]) -> RpcClient:
        addr = (addr[0], int(addr[1]))
        with self._lock:
            c = self._clients.get(key)
            if c is None or c.addr != addr:
                if c is not None:
                    c.close()
                c = RpcClient(addr, tls=self._tls,
                              verify_hostname=self._verify_hostname)
                self._clients[key] = c
            return c

    def close(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()
