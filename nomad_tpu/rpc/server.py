"""Threaded RPC server: dispatches framed requests to named handlers.

Reference: nomad/rpc.go handleConn/handleNomadConn — a goroutine per
connection decoding requests and dispatching to registered endpoints.
"""
from __future__ import annotations

import logging
import socket
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .wire import recv_frame, send_frame

_log = logging.getLogger(__name__)


class RpcServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 tls=None, region: str = "global"):
        """`tls`: an ssl.SSLContext from tlsutil.server_context —
        mutual TLS; a client with no CA-signed cert fails the
        handshake before a single frame is read (reference:
        nomad/rpc.go:99-115 wraps every conn in tls.Server).

        `region` names the server SAN role (`server.<region>.nomad`)
        that verbs registered with server_only=True require of the
        PEER's certificate — the reference's certificate-role check
        (nomad/rpc.go validateServerHostname): with mutual TLS on, a
        client-role cert must not reach raft or other server-to-server
        verbs."""
        self._handlers: Dict[str, Tuple[Callable[[List[Any]], Any],
                                        bool]] = {}
        self._tls = tls
        self.region = region
        self._server_role = f"server.{region}.nomad"
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.addr: Tuple[str, int] = self._sock.getsockname()
        self._shutdown = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    def register(self, method: str, fn: Callable[[List[Any]], Any],
                 server_only: bool = False) -> None:
        """fn receives the params list and returns a JSON-able result;
        raising RpcHandlerError sends a typed error frame.
        `server_only` verbs (raft, server-to-server forwarding) require
        the mTLS peer to present a server.<region>.nomad role cert."""
        self._handlers[method] = (fn, server_only)

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"rpc-accept-{self.addr[1]}")
        self._accept_thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _peer = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        role: Optional[str] = None
        if self._tls is not None:
            try:
                # a short handshake deadline so a plaintext client
                # can't pin the thread; cleared for the frame loop
                conn.settimeout(5.0)
                conn = self._tls.wrap_socket(conn, server_side=True)
                conn.settimeout(None)
            except (OSError, ValueError) as e:
                _log.debug("rpc tls handshake rejected: %s", e)
                try:
                    conn.close()
                except OSError:
                    pass
                return
            from ..utils.tlsutil import peer_role
            role = peer_role(conn)
        try:
            while not self._shutdown.is_set():
                try:
                    req = recv_frame(conn)
                except (ConnectionError, ValueError, OSError):
                    return
                # a stopped server must not answer a request that raced
                # the shutdown (callers probe liveness through these
                # sockets — e.g. the gossip failure detector)
                if self._shutdown.is_set():
                    return
                try:
                    resp = self._dispatch(req, role)
                    send_frame(conn, resp)
                except OSError:
                    return
                except Exception:               # noqa: BLE001
                    # malformed request shape or unserializable handler
                    # result: answer with a typed error instead of
                    # killing the connection
                    _log.exception("rpc dispatch failed")
                    try:
                        rid = req.get("id") if isinstance(req, dict) \
                            else None
                        send_frame(conn, {"id": rid, "error": {
                            "kind": "internal",
                            "message": "dispatch failed"}})
                    except OSError:
                        return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, req: Any, role: Optional[str] = None) -> Any:
        if not isinstance(req, dict):
            return {"id": None, "error": {"kind": "bad_request",
                                          "message": "frame is not an object"}}
        rid = req.get("id")
        method = req.get("method", "")
        ent = self._handlers.get(method)
        if ent is None:
            return {"id": rid, "error": {"kind": "unknown_method",
                                         "message": method}}
        fn, server_only = ent
        if server_only and self._tls is not None \
                and role != self._server_role:
            # certificate-role confusion guard: with mTLS on, ANY
            # CA-signed cert completes the handshake, but only a
            # server-role cert may speak server-to-server verbs
            _log.warning("rpc %s denied: peer role %r != %r", method,
                         role, self._server_role)
            return {"id": rid, "error": {
                "kind": "permission_denied",
                "message": f"{method} requires a "
                           f"{self._server_role} certificate"}}
        try:
            return {"id": rid, "result": fn(req.get("params", []))}
        except RpcHandlerError as e:
            return {"id": rid, "error": e.wire()}
        except Exception as e:                      # noqa: BLE001
            _log.exception("rpc handler %s failed", method)
            return {"id": rid, "error": {"kind": "internal",
                                         "message": f"{type(e).__name__}: {e}"}}


class RpcHandlerError(Exception):
    """Typed application error carried over the wire (e.g. not_leader
    with a forwarding hint)."""

    def __init__(self, kind: str, message: str = "",
                 data: Optional[Dict[str, Any]] = None):
        super().__init__(message or kind)
        self.kind = kind
        self.message = message
        self.data = data or {}

    def wire(self) -> Dict[str, Any]:
        return {"kind": self.kind, "message": self.message,
                "data": self.data}
