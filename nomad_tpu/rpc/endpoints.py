"""The server's wire verbs + the agent's wire-side ServerEndpoints.

Reference: the endpoint tables registered in nomad/server.go:1127-1150
and the client's server manager (client/servers/). Every verb wraps:
decode -> (forward to leader if this server is a follower —
nomad/rpc.go forward()) -> invoke -> encode.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..client.agent import ServerEndpoints
from ..raft.node import NotLeaderError
from ..structs import Allocation, Job, Node
from ..utils.codec import from_wire, to_wire
from .client import ClientPool, RpcClient, RpcError
from .server import RpcHandlerError, RpcServer


class ServerRpc:
    """Serves one Server's RPC verbs on an RpcServer.

    Followers forward leader-only writes to the current leader over
    their own client pool; if no leader is known the caller gets a
    typed `not_leader` error and may retry elsewhere.
    """

    def __init__(self, server, rpc_server: RpcServer,
                 peer_addrs: Optional[Dict[str, Tuple[str, int]]] = None,
                 tls=None, verify_hostname: str = ""):
        self.server = server
        self.rpc = rpc_server
        self.peer_addrs = dict(peer_addrs or {})
        # follower->leader forwarding is server-to-server: pin the
        # dialed peer's SAN role when verify_hostname is set
        self._pool = ClientPool(tls=tls, verify_hostname=verify_hostname)
        # leader_only verbs forward to the leader up front (heartbeats
        # must reset the LEADER's failure detector, not a follower's
        # disabled one — nomad/rpc.go forward() runs before the handler);
        # GetClientAllocs reads replicated state from any member (the
        # stale-read path) and Status.* is local by definition
        for method, fn, leader_only in (
            ("Node.Register", self._node_register, True),
            ("Node.Heartbeat", self._node_heartbeat, True),
            ("Node.GetClientAllocs", self._get_client_allocs, False),
            ("Node.UpdateAlloc", self._update_alloc, True),
            ("Secret.Get", self._secret_get, False),
            ("Alloc.MigrateSource", self._alloc_migrate_source, False),
            ("Job.Register", self._job_register, True),
            ("Job.Deregister", self._job_deregister, True),
            ("Status.Leader", self._status_leader, False),
            ("Status.Peers", self._status_peers, False),
        ):
            self.rpc.register(method,
                              self._forwarding(method, fn, leader_only))

    # ----------------------------------------------------------- verbs
    def _node_register(self, params):
        node = from_wire(Node, params[0])
        return self.server.register_node(node)

    def _node_heartbeat(self, params):
        return self.server.node_heartbeat(params[0])

    def _get_client_allocs(self, params):
        node_id, min_index, timeout = params
        allocs, index = self.server.get_client_allocs(
            node_id, int(min_index), float(timeout))
        return [[to_wire(a) for a in allocs], index]

    def _update_alloc(self, params):
        updates = [from_wire(Allocation, u) for u in params[0]]
        return self.server.update_allocs_from_client(updates)

    def _secret_get(self, params):
        namespace, path = params
        return self.server.store.secret_by_path(namespace, path)

    def _alloc_migrate_source(self, params):
        return self.server.alloc_migrate_source(params[0])

    def _job_register(self, params):
        job = from_wire(Job, params[0])
        ev = self.server.register_job(job)
        return to_wire(ev) if ev is not None else None

    def _job_deregister(self, params):
        namespace, job_id, purge = params
        ev = self.server.deregister_job(namespace, job_id, purge)
        return to_wire(ev) if ev is not None else None

    def _status_leader(self, params):
        if self.server.is_leader():
            return self.server.raft.id
        return self.server.raft.leader_id

    def _status_peers(self, params):
        return {pid: list(addr) for pid, addr in self.peer_addrs.items()}

    # ------------------------------------------------------ forwarding
    def _forwarding(self, method: str, fn, leader_only: bool):
        def wrapped(params):
            if leader_only and not self.server.is_leader():
                return self._forward(method, params,
                                     self.server.raft.leader_id)
            try:
                return fn(params)
            except NotLeaderError as e:
                # lost leadership mid-call: hand off
                return self._forward(method, params, e.leader_id
                                     or self.server.raft.leader_id)
        return wrapped

    def _forward(self, method: str, params, leader: Optional[str]):
        addr = self.peer_addrs.get(leader) if leader else None
        if addr is None or leader == self.server.raft.id:
            raise RpcHandlerError("not_leader", "no known leader",
                                  {"leader": leader})
        try:
            return self._pool.get(leader, addr).call(method, params)
        except (ConnectionError, RpcError) as fe:
            raise RpcHandlerError("forward_failed", str(fe),
                                  {"leader": leader}) from fe


class RpcServerEndpoints(ServerEndpoints):
    """The node agent's server surface over the wire, with server-list
    failover (reference: client/servers/ rebalancing — on a transport
    error the next server in the list is tried)."""

    def __init__(self, addrs: Sequence[Tuple[str, int]], tls=None):
        assert addrs, "need at least one server address"
        self.addrs = [(h, int(p)) for h, p in addrs]
        self._clients = [RpcClient(a, tls=tls) for a in self.addrs]
        self._current = 0
        self._lock = threading.Lock()

    def _call(self, method: str, params: List[Any],
              timeout: float = 30.0):
        last: Optional[Exception] = None
        n = len(self._clients)
        for attempt in range(n):
            with self._lock:
                ix = self._current
            client = self._clients[ix]
            try:
                return client.call(method, params, timeout=timeout)
            except (ConnectionError, RpcError) as e:
                if isinstance(e, RpcError) and e.kind not in (
                        "not_leader", "forward_failed"):
                    raise
                last = e
                with self._lock:
                    self._current = (ix + 1) % n
        raise last if last is not None else ConnectionError("no servers")

    # -------------------------------------------------- ServerEndpoints
    def register_node(self, node: Node) -> int:
        return self._call("Node.Register", [to_wire(node)])

    def node_heartbeat(self, node_id: str) -> Optional[float]:
        return self._call("Node.Heartbeat", [node_id])

    def get_client_allocs(self, node_id: str, min_index: int,
                          timeout: float):
        allocs_wire, index = self._call(
            "Node.GetClientAllocs", [node_id, min_index, timeout],
            timeout=timeout + 10.0)
        return ([from_wire(Allocation, a) for a in allocs_wire], index)

    def update_allocs(self, updates: List[Allocation]) -> None:
        self._call("Node.UpdateAlloc",
                   [[to_wire(u) for u in updates]])

    def get_secret(self, namespace: str, path: str):
        return self._call("Secret.Get", [namespace, path])

    def get_alloc_migrate_source(self, alloc_id: str):
        return self._call("Alloc.MigrateSource", [alloc_id])

    # convenience for tests / CLI parity over the wire
    def register_job(self, job: Job):
        return self._call("Job.Register", [to_wire(job)])


def serve_cluster(n: int = 3, host: str = "127.0.0.1", num_workers: int = 1,
                  server_kwargs: Optional[dict] = None,
                  tls_server=None, tls_client=None,
                  verify_hostname: str = ""):
    """Boot an n-server cluster wired over TCP: one RpcServer per member
    carrying both the raft verbs and the server endpoints. Returns
    (servers, server_rpcs, addrs). The reference's in-process test
    cluster (nomad/testing.go TestJoin) with real sockets."""
    from ..raft import RaftConfig
    from ..server.server import Server
    from .transport import TcpRaftTransport

    ids = [f"s{i + 1}" for i in range(n)]
    rpcs = [RpcServer(host, 0, tls=tls_server) for _ in ids]
    addrs = {pid: rpc.addr for pid, rpc in zip(ids, rpcs)}
    servers, server_rpcs = [], []
    for pid, rpc in zip(ids, rpcs):
        transport = TcpRaftTransport(rpc, addrs, tls=tls_client,
                                     verify_hostname=verify_hostname)
        srv = Server(num_workers=num_workers,
                     raft_config=RaftConfig(node_id=pid, peers=list(ids)),
                     raft_transport=transport,
                     **(server_kwargs or {}))
        server_rpcs.append(ServerRpc(srv, rpc, addrs, tls=tls_client,
                                     verify_hostname=verify_hostname))
        servers.append(srv)
        rpc.start()
    for srv in servers:
        srv.start()
    return servers, server_rpcs, addrs
