"""Wire RPC: length-prefixed JSON over TCP.

Reference: nomad/rpc.go — msgpack-RPC over yamux/TCP with region/leader
forwarding. The TPU build keeps the same three roles on one simpler
substrate (framed JSON over plain TCP, one in-flight request per pooled
connection):

  * RpcServer / RpcClient — the request/response substrate
    (nomad/rpc.go:24 handleConn + helper/pool ConnPool).
  * TcpRaftTransport — raft's peer transport (nomad/raft_rpc.go),
    pluggable against the same RaftNode the in-process transport drives.
  * ServerRpc — the server's RPC verbs (Node.*, Job.*, Status.*) with
    follower->leader forwarding (nomad/rpc.go forward()).
  * RpcServerEndpoints — the client agent's ServerEndpoints over the
    wire, with server-list failover (client/servers/).
"""
from .client import RpcClient, RpcError
from .endpoints import RpcServerEndpoints, ServerRpc
from .server import RpcServer
from .transport import TcpRaftTransport

__all__ = ["RpcClient", "RpcError", "RpcServer", "RpcServerEndpoints",
           "ServerRpc", "TcpRaftTransport"]
