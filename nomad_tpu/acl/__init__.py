"""ACL: tokens, policies, capability checks.

Reference: acl/acl.go (compiled ACL object + capability checks),
acl/policy.go (policy schema), nomad/acl.go (token resolution),
nomad/acl_endpoint.go (bootstrap/upsert verbs). Policies here are
JSON-shaped rather than HCL1 — the jobspec layer already made that
trade (SURVEY §5.6) — with the same namespace/node/agent/operator rule
classes, coarse policy levels and fine-grained capabilities.
"""
from .acl import (CAPABILITIES, ACL, ACLPolicy, ACLToken, NamespaceRule,
                  compile_acl, management_acl)

__all__ = ["ACL", "ACLPolicy", "ACLToken", "CAPABILITIES",
           "NamespaceRule", "compile_acl", "management_acl"]
