"""Compiled ACLs (reference: acl/acl.go, acl/policy.go).

A token names policies; policies carry namespace rules (coarse policy
level and/or fine-grained capabilities), plus node/agent/operator
levels. `compile_acl` merges any number of policies into one ACL whose
checks the endpoints consult. Namespace rules support exact names and
a trailing-* glob (the reference uses full glob matching; prefix
globs cover its documented uses)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_WRITE = "write"
_LEVEL = {POLICY_DENY: 0, "": 0, POLICY_READ: 1, POLICY_WRITE: 2}

# namespace capabilities (reference: acl/policy.go:47-76)
CAP_DENY = "deny"
CAP_LIST_JOBS = "list-jobs"
CAP_READ_JOB = "read-job"
CAP_SUBMIT_JOB = "submit-job"
CAP_DISPATCH_JOB = "dispatch-job"
CAP_READ_LOGS = "read-logs"
CAP_READ_FS = "read-fs"
CAP_ALLOC_EXEC = "alloc-exec"
CAP_ALLOC_LIFECYCLE = "alloc-lifecycle"
CAP_CSI_REGISTER_PLUGIN = "csi-register-plugin"
CAP_CSI_WRITE_VOLUME = "csi-write-volume"
CAP_CSI_READ_VOLUME = "csi-read-volume"
CAP_CSI_LIST_VOLUME = "csi-list-volume"
CAPABILITIES = (CAP_LIST_JOBS, CAP_READ_JOB, CAP_SUBMIT_JOB,
                CAP_DISPATCH_JOB, CAP_READ_LOGS, CAP_READ_FS,
                CAP_ALLOC_EXEC, CAP_ALLOC_LIFECYCLE,
                CAP_CSI_REGISTER_PLUGIN, CAP_CSI_WRITE_VOLUME,
                CAP_CSI_READ_VOLUME, CAP_CSI_LIST_VOLUME)

_READ_CAPS = {CAP_LIST_JOBS, CAP_READ_JOB, CAP_CSI_LIST_VOLUME,
              CAP_CSI_READ_VOLUME}
_WRITE_CAPS = _READ_CAPS | {
    CAP_SUBMIT_JOB, CAP_DISPATCH_JOB, CAP_READ_LOGS, CAP_READ_FS,
    CAP_ALLOC_EXEC, CAP_ALLOC_LIFECYCLE, CAP_CSI_WRITE_VOLUME}


@dataclass
class NamespaceRule:
    name: str = "default"            # exact, or trailing-* glob
    policy: str = ""                 # deny|read|write
    capabilities: List[str] = field(default_factory=list)

    def expanded_capabilities(self) -> set:
        caps = set(self.capabilities)
        if self.policy == POLICY_READ:
            caps |= _READ_CAPS
        elif self.policy == POLICY_WRITE:
            caps |= _WRITE_CAPS
        if self.policy == POLICY_DENY or CAP_DENY in caps:
            return {CAP_DENY}
        return caps


@dataclass
class ACLPolicy:
    name: str = ""
    description: str = ""
    namespaces: List[NamespaceRule] = field(default_factory=list)
    node: str = ""                   # deny|read|write
    agent: str = ""
    operator: str = ""
    create_index: int = 0
    modify_index: int = 0


@dataclass
class ACLToken:
    accessor_id: str = ""
    secret_id: str = ""
    name: str = ""
    type: str = "client"             # client | management
    policies: List[str] = field(default_factory=list)
    global_: bool = False
    create_index: int = 0
    modify_index: int = 0

    def is_management(self) -> bool:
        return self.type == "management"


class ACL:
    """Compiled capability matrix (reference: acl/acl.go ACL)."""

    def __init__(self, management: bool = False):
        self.management = management
        self._ns_caps: Dict[str, set] = {}       # rule name -> caps
        self.node = ""
        self.agent = ""
        self.operator = ""

    # -- namespaces --
    def _caps_for(self, namespace: str) -> set:
        """Longest-match rule wins (reference: acl.go
        AllowNamespaceOperation's glob resolution)."""
        best, best_len = set(), -1
        for pattern, caps in self._ns_caps.items():
            if pattern == namespace:
                return caps
            if pattern.endswith("*") \
                    and namespace.startswith(pattern[:-1]) \
                    and len(pattern) > best_len:
                best, best_len = caps, len(pattern)
        return best

    def allow_namespace_op(self, namespace: str, cap: str) -> bool:
        if self.management:
            return True
        caps = self._caps_for(namespace)
        return cap in caps and CAP_DENY not in caps

    def allow_namespace(self, namespace: str) -> bool:
        """Any access at all (reference: acl.go AllowNamespace)."""
        if self.management:
            return True
        caps = self._caps_for(namespace)
        return bool(caps) and CAP_DENY not in caps

    # -- coarse scopes --
    def allow_node_read(self) -> bool:
        return self.management or _LEVEL[self.node] >= 1

    def allow_node_write(self) -> bool:
        return self.management or _LEVEL[self.node] >= 2

    def allow_agent_read(self) -> bool:
        return self.management or _LEVEL[self.agent] >= 1

    def allow_agent_write(self) -> bool:
        return self.management or _LEVEL[self.agent] >= 2

    def allow_operator_read(self) -> bool:
        return self.management or _LEVEL[self.operator] >= 1

    def allow_operator_write(self) -> bool:
        return self.management or _LEVEL[self.operator] >= 2


def compile_acl(policies: Sequence[ACLPolicy]) -> ACL:
    """Merge policies; within one namespace rule name, capability sets
    union and an explicit deny dominates (acl.go NewACL)."""
    acl = ACL()
    for p in policies:
        for rule in p.namespaces:
            caps = rule.expanded_capabilities()
            cur = acl._ns_caps.setdefault(rule.name, set())
            if CAP_DENY in caps or CAP_DENY in cur:
                acl._ns_caps[rule.name] = {CAP_DENY}
            else:
                cur |= caps
        for scope in ("node", "agent", "operator"):
            lvl = getattr(p, scope)
            if _LEVEL[lvl] > _LEVEL[getattr(acl, scope)]:
                setattr(acl, scope, lvl)
    return acl


def management_acl() -> ACL:
    return ACL(management=True)
