"""Pass 2: jit purity / retrace hazards.

Functions traced under jax.jit / pjit / pallas run as captured device
programs: host side effects execute only at trace time (so they
silently vanish on cache hits, or fire once per retrace), Python
branching on non-static arguments raises or — worse, via weak types and
`int` promotion — retraces per value, and donated buffers are dead the
moment the call is dispatched.  Any of these in the solver dispatch
path silently regresses the PR 1/2 wins into per-eval recompiles.

Rules
  JIT201  host side effect (I/O, logging, env, clock, randomness)
          reachable from a jit/pallas root
  JIT202  global/closure mutation reachable from a jit/pallas root
          (trace-time write = tracer leak / stale capture)
  JIT203  non-static jit parameter used in Python control flow
          (retrace bomb / trace error) — if/while/ternary tests AND
          `for _ in range(param)` loop bounds (the shortlist-era
          kernel surface: widths like shortlist_c drive Python loop
          unrolling and MUST be static)
  JIT204  buffer passed at a donated position read again after the
          dispatch — including subscript/attribute reads through the
          donated name (`carry[0]` after donating `carry`, the
          wave-loop carry shape)
  JIT205  collective primitive (lax.psum / all_gather / ppermute /
          axis_index ...) invoked outside a mesh context — the
          function is not reachable from any shard_map/pmap root, so
          the axis name cannot be bound and the call raises (or, in a
          refactor that drops the shard_map wrapper, turns the mesh-
          resident solve into a latent trace error).  Also covers
          wrong-axis collectives under statically-known meshes —
          including three-level ("regions", "hosts", "chips") tuples,
          meshes built by an internal helper (make_three_tier_mesh
          style: one return-level deep), and axes bound only by an
          INNER nested context while the body is also reachable from
          an outer mesh (ISSUE 13)
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisConfig, Finding, PackageIndex, _dotted

HOST_EFFECT_EXACT = {"print", "input", "open", "exec", "eval"}
HOST_EFFECT_PREFIXES = (
    "os.", "sys.", "io.", "logging.", "time.", "random.",
    "numpy.random.", "np.random.", "subprocess.", "socket.",
    "builtins.print", "shutil.", "pathlib.",
)
# benign stdlib the prefixes above would otherwise catch
HOST_EFFECT_ALLOW = {"os.path.join", "os.path.dirname",
                     "os.path.abspath", "os.path.basename"}
LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
               "critical", "log"}

JIT_NAMES = {"jax.jit", "jit", "functools.partial", "partial",
             "jax.pjit", "pjit"}

# collective primitives that require a bound mesh axis name
COLLECTIVE_SUFFIXES = (
    "lax.psum", "lax.pmean", "lax.pmax", "lax.pmin", "lax.all_gather",
    "lax.ppermute", "lax.pshuffle", "lax.all_to_all", "lax.axis_index",
    "lax.psum_scatter",
)


def _is_collective(name: str) -> bool:
    return any(name == s or name.endswith("." + s)
               for s in COLLECTIVE_SUFFIXES)


def _is_mesh_wrapper(full: str) -> bool:
    """shard_map / pmap / xmap call names (any import spelling)."""
    return (full.endswith("shard_map") or full in ("jax.pmap", "pmap")
            or full.endswith(".pmap") or full.endswith("xmap"))


def _module_str_constants(index: PackageIndex,
                          module: str) -> Dict[str, str]:
    """Module-level `NAME = "literal"` string constants (the axis-name
    spelling: MESH_HOST_AXIS = "hosts")."""
    cache = getattr(index, "_str_const_cache", None)
    if cache is None:
        cache = index._str_const_cache = {}
    out = cache.get(module)
    if out is not None:
        return out
    out = {}
    mi = index.modules.get(module)
    if mi is not None:
        for node in mi.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                out[node.targets[0].id] = node.value.value
    cache[module] = out
    return out


def _axis_str(index: PackageIndex, fi, aliases: Dict[str, str],
              node) -> Optional[str]:
    """Resolve an expression to an axis-name string: a literal, or a
    Name/Attribute bound to a module-level string constant (local or
    imported)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    d = _dotted(node)
    if not d:
        return None
    head = d.split(".")[0]
    target = aliases.get(head)
    if target is not None:
        d = target + d[len(head):]
    if "." in d:
        mod, name = d.rsplit(".", 1)
        return _module_str_constants(index, mod).get(name)
    return _module_str_constants(index, fi.module).get(d)


def _mesh_ctor_axes(index: PackageIndex, fi, aliases: Dict[str, str],
                    call: ast.Call) -> Optional[Set[str]]:
    """Axis names bound by a `Mesh(devices, ("a", "b"))` constructor
    call with statically resolvable names; None when unresolvable."""
    names_arg = None
    if len(call.args) >= 2:
        names_arg = call.args[1]
    for kw in call.keywords:
        if kw.arg == "axis_names":
            names_arg = kw.value
    if names_arg is None:
        return None
    elts = (names_arg.elts if isinstance(names_arg, (ast.Tuple, ast.List))
            else [names_arg])
    axes: Set[str] = set()
    for e in elts:
        s = _axis_str(index, fi, aliases, e)
        if s is None:
            return None
        axes.add(s)
    return axes or None


def _helper_mesh_axes(index: PackageIndex,
                      fkey: Optional[str]) -> Optional[Set[str]]:
    """Axis names bound by a Mesh an internal helper constructs and
    returns (the make_three_tier_mesh shape: `mesh=make_mesh(...)` at
    the shard_map call site).  Follows ONE level: every return path
    must be a visible `Mesh(devs, (...))` ctor (or a local bound to
    one) with statically resolvable names; multiple return paths keep
    only the axes bound on EVERY path.  None = not provable."""
    fi = index.functions.get(fkey) if fkey else None
    if fi is None:
        return None
    aliases = dict(index.modules[fi.module].aliases)
    aliases.update(index._local_imports(fi))

    def _full(node) -> str:
        d = _dotted(node)
        if not d:
            return ""
        head = d.split(".")[0]
        resolved = aliases.get(head)
        return (resolved + d[len(head):]) if resolved else d

    mesh_locals: Dict[str, Optional[Set[str]]] = {}
    for node in index._own_nodes(fi):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _full(node.value.func).endswith("Mesh"):
            mesh_locals[node.targets[0].id] = _mesh_ctor_axes(
                index, fi, aliases, node.value)
    axes: Optional[Set[str]] = None
    saw_return = False
    for node in index._own_nodes(fi):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        saw_return = True
        v = node.value
        if isinstance(v, ast.Call) and _full(v.func).endswith("Mesh"):
            a = _mesh_ctor_axes(index, fi, aliases, v)
        elif isinstance(v, ast.Name):
            a = mesh_locals.get(v.id)
        else:
            a = None
        if a is None:
            return None
        axes = a if axes is None else (axes & a)
    return axes if saw_return and axes else None


def find_mesh_roots(index: PackageIndex) -> List[str]:
    """Functions handed to shard_map/pmap — the roots under which a
    collective primitive has a bound axis name (see
    find_mesh_roots_with_axes for the per-root bound-axis sets)."""
    return list(find_mesh_roots_with_axes(index))


def find_mesh_roots_with_axes(
        index: PackageIndex) -> Dict[str, Optional[Set[str]]]:
    """Mesh roots -> the axis names their enclosing mesh context binds
    (ISSUE 8: nested ("hosts", "chips") axes make a wrong-axis psum a
    real hazard).  Resolves the direct callable, a
    functools.partial(f, ...) wrapper, and a local
    `name = functools.partial(f, ...)` binding; the bound axes come
    from the shard_map call's `mesh=` argument when it is a local
    `m = Mesh(devs, ("a", "b"))` binding with literal (or module-
    constant) names, or pmap's literal `axis_name=`.  None = the
    context exists but its axes are not statically resolvable (a mesh
    passed in as a parameter) — the axis check stays silent there."""
    roots: Dict[str, Optional[Set[str]]] = {}
    for fkey, fi in index.functions.items():
        la = index._local_imports(fi)
        lt = index._local_var_types(fi)
        aliases = dict(index.modules[fi.module].aliases)
        aliases.update(la)

        def _full(node) -> str:
            d = _dotted(node)
            if not d:
                return ""
            head = d.split(".")[0]
            resolved = aliases.get(head)
            return (resolved + d[len(head):]) if resolved else d

        def _target_of(node):
            """Resolve a callable expression to an internal func key:
            bare name/attr, or functools.partial(f, ...)."""
            if isinstance(node, ast.Call) and \
                    _full(node.func) in ("functools.partial", "partial") \
                    and node.args:
                node = node.args[0]
            if isinstance(node, (ast.Name, ast.Attribute)):
                return index.resolve_call(
                    fi, ast.Call(func=node, args=[], keywords=[]),
                    la, lt)
            return None

        # local `body = functools.partial(f, ...)` bindings, and local
        # `m = Mesh(devs, ("a", "b"))` mesh constructions
        partial_locals: Dict[str, Optional[str]] = {}
        mesh_locals: Dict[str, Optional[Set[str]]] = {}
        for node in index._own_nodes(fi):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                full_v = _full(node.value.func)
                if full_v.endswith("Mesh"):
                    mesh_locals[node.targets[0].id] = _mesh_ctor_axes(
                        index, fi, aliases, node.value)
                    continue
                # `m = make_three_tier_mesh(...)`: an internal helper
                # returning a Mesh binds axes just as a local ctor does
                hk = index.resolve_call(fi, node.value, la, lt)
                hx = _helper_mesh_axes(index, hk)
                if hx is not None:
                    mesh_locals[node.targets[0].id] = hx
                    continue
                tgt = _target_of(node.value)
                if tgt:
                    partial_locals[node.targets[0].id] = tgt

        def _axes_of_call(call: ast.Call) -> Optional[Set[str]]:
            """Bound axes of one shard_map/pmap call site, if
            statically resolvable."""
            for kw in call.keywords:
                if kw.arg == "axis_name":          # pmap spelling
                    s = _axis_str(index, fi, aliases, kw.value)
                    return {s} if s is not None else None
                if kw.arg == "mesh":
                    if isinstance(kw.value, ast.Call):
                        if _full(kw.value.func).endswith("Mesh"):
                            return _mesh_ctor_axes(index, fi, aliases,
                                                   kw.value)
                        return _helper_mesh_axes(
                            index, index.resolve_call(fi, kw.value,
                                                      la, lt))
                    if isinstance(kw.value, ast.Name):
                        return mesh_locals.get(kw.value.id)
                    return None
            return None

        for node in index._own_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            full = _full(node.func)
            if not full or not _is_mesh_wrapper(full) or not node.args:
                continue
            arg0 = node.args[0]
            if isinstance(arg0, ast.Name) and arg0.id in partial_locals:
                tgt = partial_locals[arg0.id]
            else:
                tgt = _target_of(arg0)
            if tgt:
                axes = _axes_of_call(node)
                if tgt in roots:
                    # several contexts wrap the same body: only axes
                    # EVERY known context binds are provably safe — an
                    # axis bound only by an inner three-tier context
                    # still trace-fails when the body runs under the
                    # outer mesh (ISSUE 13's nested-region hazard);
                    # any unresolvable context still silences the check
                    prev = roots[tgt]
                    roots[tgt] = (prev & axes
                                  if prev is not None and axes is not None
                                  else None)
                else:
                    roots[tgt] = axes
    return roots


class JitRoot:
    __slots__ = ("fkey", "static", "donate", "via")

    def __init__(self, fkey: str, static: Set[str],
                 donate: Tuple[int, ...], via: str):
        self.fkey = fkey
        self.static = static
        self.donate = donate
        self.via = via      # "decorator" | "call" | "pallas"


def _const_tuple(node) -> Tuple:
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant))
    if isinstance(node, ast.Constant):
        return (node.value,)
    return ()


def _jit_kwargs(call: ast.Call) -> Tuple[Set[str], Tuple[int, ...]]:
    static: Set[str] = set()
    donate: Tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            static |= {s for s in _const_tuple(kw.value)
                       if isinstance(s, str)}
        elif kw.arg == "static_argnums":
            static |= {f"#{i}" for i in _const_tuple(kw.value)
                       if isinstance(i, int)}
        elif kw.arg == "donate_argnums":
            donate = tuple(i for i in _const_tuple(kw.value)
                           if isinstance(i, int))
    return static, donate


def _is_jit_call(node: ast.Call, aliases: Dict[str, str]) -> bool:
    d = _dotted(node.func)
    if not d:
        return False
    head = d.split(".")[0]
    resolved = aliases.get(head)
    if resolved:
        d = resolved + d[len(head):]
    return d in ("jax.jit", "jax.pjit") or d.endswith(".jit")


def _unwrap_partial(node: ast.Call, aliases: Dict[str, str]
                    ) -> Optional[ast.Call]:
    """functools.partial(jax.jit, ...) -> the jit call carrying the
    kwargs."""
    d = _dotted(node.func)
    if not d:
        return None
    head = d.split(".")[0]
    resolved = aliases.get(head)
    full = (resolved + d[len(head):]) if resolved else d
    if full in ("functools.partial", "partial") and node.args:
        inner = node.args[0]
        inner_d = _dotted(inner)
        if inner_d:
            ih = inner_d.split(".")[0]
            ir = aliases.get(ih)
            ifull = (ir + inner_d[len(ih):]) if ir else inner_d
            if ifull in ("jax.jit", "jax.pjit"):
                return node
    return None


def find_jit_roots(index: PackageIndex) -> List[JitRoot]:
    roots: List[JitRoot] = []
    for fkey, fi in index.functions.items():
        aliases = index.modules[fi.module].aliases
        for dec in getattr(fi.node, "decorator_list", ()):
            if isinstance(dec, ast.Call):
                p = _unwrap_partial(dec, aliases)
                if p is not None:
                    static, donate = _jit_kwargs(p)
                    roots.append(JitRoot(fkey, static, donate,
                                         "decorator"))
                elif _is_jit_call(dec, aliases):
                    static, donate = _jit_kwargs(dec)
                    roots.append(JitRoot(fkey, static, donate,
                                         "decorator"))
            else:
                d = _dotted(dec)
                if d:
                    head = d.split(".")[0]
                    full = ((aliases.get(head) or head)
                            + d[len(head):]) if head else d
                    if full in ("jax.jit", "jax.pjit", "jit"):
                        roots.append(JitRoot(fkey, set(), (),
                                             "decorator"))
    # call-form roots: jax.jit(f, ...) / pl.pallas_call(kernel, ...)
    for fkey, fi in index.functions.items():
        la = index._local_imports(fi)
        aliases = dict(index.modules[fi.module].aliases)
        aliases.update(la)
        for node in index._own_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if not d:
                continue
            head = d.split(".")[0]
            full = (aliases.get(head) or head) + d[len(head):]
            if full in ("jax.jit", "jax.pjit") and node.args:
                target = index.resolve_call(
                    fi, ast.Call(func=node.args[0], args=[],
                                 keywords=[]), la,
                    index._local_var_types(fi)) \
                    if isinstance(node.args[0],
                                  (ast.Name, ast.Attribute)) else None
                if target:
                    static, donate = _jit_kwargs(node)
                    roots.append(JitRoot(target, static, donate,
                                         "call"))
            elif full.endswith("pallas_call") and node.args:
                if isinstance(node.args[0], (ast.Name, ast.Attribute)):
                    target = index.resolve_call(
                        fi, ast.Call(func=node.args[0], args=[],
                                     keywords=[]), la,
                        index._local_var_types(fi))
                    if target:
                        roots.append(JitRoot(target, set(), (),
                                             "pallas"))
    return roots


def run_jit_pass(index: PackageIndex, cfg: AnalysisConfig
                 ) -> List[Finding]:
    findings: List[Finding] = []
    roots = find_jit_roots(index)
    root_keys = [r.fkey for r in roots]
    reach = index.reachable(root_keys)

    # ---- JIT201 / JIT202 over the traced closure
    for fkey in sorted(reach):
        fi = index.functions[fkey]
        for name, lineno in index.external_calls(fkey):
            if _is_host_effect(name):
                findings.append(Finding(
                    "JIT201", fi.module, fi.qual, name, fi.path, lineno,
                    f"host side effect `{name}` inside a jit/pallas-"
                    "traced closure; it runs at trace time only and "
                    "vanishes on cache hits",
                    hint="hoist the effect to the dispatch wrapper, or "
                         "baseline if it is a deliberate trace-time "
                         "config probe"))
        for node in index._own_nodes(fi):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                base = _dotted(node.func.value)
                if base and node.func.attr in LOG_METHODS and \
                        _looks_like_logger(base):
                    findings.append(Finding(
                        "JIT201", fi.module, fi.qual,
                        f"{base}.{node.func.attr}", fi.path,
                        node.lineno,
                        f"logging call `{base}.{node.func.attr}` "
                        "inside a jit/pallas-traced closure",
                        hint="log from the dispatch wrapper instead"))
            if isinstance(node, ast.Global):
                findings.append(Finding(
                    "JIT202", fi.module, fi.qual,
                    ",".join(node.names), fi.path, node.lineno,
                    "global-statement write inside a jit/pallas-traced "
                    "closure; trace-time mutation leaks tracers and "
                    "goes stale on cache hits",
                    hint="return the value from the traced function "
                         "and assign it on the host"))
        # subscript/attr stores on module globals
        mi = index.modules[fi.module]
        for node in index._own_nodes(fi):
            tgt = None
            if isinstance(node, ast.Assign):
                tgt = node.targets
            elif isinstance(node, ast.AugAssign):
                tgt = [node.target]
            if not tgt:
                continue
            for t in tgt:
                base = t
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Name) and \
                        base.id in mi.globals and base is not t:
                    findings.append(Finding(
                        "JIT202", fi.module, fi.qual, base.id,
                        fi.path, node.lineno,
                        f"mutation of module global `{base.id}` inside "
                        "a jit/pallas-traced closure",
                        hint="mutate from the un-traced wrapper"))

    # ---- JIT203: non-static params in Python control flow
    for r in roots:
        fi = index.functions.get(r.fkey)
        if fi is None:
            continue
        args = fi.node.args
        names = [a.arg for a in list(args.args)
                 + list(args.posonlyargs) + list(args.kwonlyargs)]
        static = set()
        for s in r.static:
            if s.startswith("#"):
                i = int(s[1:])
                if i < len(names):
                    static.add(names[i])
            else:
                static.add(s)
        traced = [n for n in names if n not in static and n != "self"]
        for node in index._own_nodes(fi):
            test = None
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            elif isinstance(node, ast.For):
                # `for _ in range(param)`: the loop unrolls at trace
                # time — a traced bound retraces per value exactly like
                # a traced `if` (the shortlist-width class of hazard)
                it = node.iter
                if isinstance(it, ast.Call) and \
                        _dotted(it.func) in ("range", "builtins.range"):
                    test = it
            if test is None:
                continue
            for sub in ast.walk(test):
                if isinstance(sub, ast.Name) and sub.id in traced:
                    findings.append(Finding(
                        "JIT203", fi.module, fi.qual, sub.id, fi.path,
                        node.lineno,
                        f"traced parameter `{sub.id}` drives Python "
                        "control flow inside a jit root; every new "
                        "value retraces (or errors) instead of "
                        "compiling once",
                        hint="mark it in static_argnames, or express "
                             "the branch with lax.cond/jnp.where"))

    # ---- JIT205: collectives outside a mesh/shard_map context
    mesh_roots = find_mesh_roots_with_axes(index)
    mesh_ok = index.reachable(mesh_roots)
    # per-function INTERSECTION of the axis names the enclosing mesh
    # contexts provably bind: an axis bound only by an inner nested
    # context (a "regions" psum in a helper also reachable from the
    # two-tier mesh) is a latent trace error on the outer path, so
    # only every-context axes count as bound; None = some context is
    # statically unresolvable, so the axis-binding check stays silent
    # (ISSUE 8 two-tier, ISSUE 13 three-tier)
    fn_axes: Dict[str, Optional[Set[str]]] = {}
    for root, axes in mesh_roots.items():
        for fkey in index.reachable([root]):
            if fkey in fn_axes:
                prev = fn_axes[fkey]
                fn_axes[fkey] = (prev & axes
                                 if prev is not None and axes is not None
                                 else None)
            else:
                fn_axes[fkey] = set(axes) if axes is not None else None
    for fkey, fi in sorted(index.functions.items()):
        if fkey in mesh_ok:
            bound = fn_axes.get(fkey)
            if not bound:
                continue
            la = index._local_imports(fi)
            aliases = dict(index.modules[fi.module].aliases)
            aliases.update(la)

            def _full(node, _a=aliases) -> str:
                d = _dotted(node)
                if not d:
                    return ""
                head = d.split(".")[0]
                resolved = _a.get(head)
                return (resolved + d[len(head):]) if resolved else d

            for node in index._own_nodes(fi):
                if not isinstance(node, ast.Call) \
                        or not _is_collective(_full(node.func)):
                    continue
                exprs = list(node.args) + [
                    kw.value for kw in node.keywords
                    if kw.arg in ("axis_name", "axis_names")]
                for e in exprs:
                    elts = (e.elts if isinstance(e, (ast.Tuple,
                                                     ast.List))
                            else [e])
                    for el in elts:
                        s = _axis_str(index, fi, aliases, el)
                        if s is not None and s not in bound:
                            findings.append(Finding(
                                "JIT205", fi.module, fi.qual,
                                _full(node.func), fi.path, node.lineno,
                                f"collective axis name {s!r} is not "
                                "bound by the enclosing mesh context "
                                f"(bound: {sorted(bound)}); under "
                                "nested mesh axes this psum/gather "
                                "reduces over the wrong tier or fails "
                                "at trace time",
                                hint="use an axis name the wrapping "
                                     "shard_map's mesh actually "
                                     "carries, or thread the axis in "
                                     "as a parameter"))
            continue
        for name, lineno in index.external_calls(fkey):
            if _is_collective(name):
                findings.append(Finding(
                    "JIT205", fi.module, fi.qual, name, fi.path, lineno,
                    f"collective primitive `{name}` invoked outside a "
                    "mesh/shard_map context: no axis name can be bound "
                    "here, the call fails at trace time",
                    hint="run the function under shard_map/pmap (or "
                         "thread it from a mesh root), or gate the "
                         "collective on the mesh_axis parameter"))

    # ---- JIT204: donated buffers read after dispatch
    donating: Dict[str, Tuple[int, ...]] = {}
    for r in roots:
        if r.donate:
            donating[r.fkey] = r.donate
    # wrappers that forward to a donating jit (one hop), e.g.
    # delta_scatter_set -> _delta_scatter("set")(arr, ...)
    wrapper_names: Dict[str, Tuple[int, ...]] = {}
    for fkey, fi in index.functions.items():
        for callee in index.callees(fkey):
            if callee in donating and fi.parent is None:
                wrapper_names.setdefault(fkey, donating[callee])
    for fkey, fi in sorted(index.functions.items()):
        callees = index.callees(fkey)
        targets = {c: donating[c] for c in callees if c in donating}
        targets.update({c: wrapper_names[c] for c in callees
                        if c in wrapper_names and c != fkey})
        if not targets:
            continue
        findings.extend(_check_donated_reads(index, fi, targets))
    return findings


def _looks_like_logger(base: str) -> bool:
    last = base.rsplit(".", 1)[-1].lstrip("_")
    return last in ("log", "logger", "logging")


def _is_host_effect(name: str) -> bool:
    if name in HOST_EFFECT_ALLOW:
        return False
    if name in HOST_EFFECT_EXACT:
        return True
    return any(name.startswith(p) for p in HOST_EFFECT_PREFIXES)


def _check_donated_reads(index: PackageIndex, fi,
                         targets: Dict[str, Tuple[int, ...]],
                         rule: str = "JIT204") -> List[Finding]:
    """Linear scan of the caller: after a call that donates `name` (or
    self-contained subscript), a load of the same expression without an
    intervening rebind is a read of a dead buffer."""
    findings: List[Finding] = []
    la = index._local_imports(fi)
    lt = index._local_var_types(fi)
    # single-assignment local aliases of attribute chains
    # (`dn = self._dev_node`): a buffer donated through the alias is
    # dead through the attribute path too — the ISSUE-7 eviction-plane
    # carry pattern (`dn["ev_prio"] = scatter(dn["ev_prio"], ...)` vs
    # a later `self._dev_node["ev_prio"]` read).  Every key below is
    # canonicalized onto the aliased expression, so rebinds through
    # either spelling suppress correctly.
    alias_counts: Dict[str, int] = {}
    aliases: Dict[str, str] = {}
    for node in index._own_nodes(fi):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            alias_counts[name] = alias_counts.get(name, 0) + 1
            if isinstance(node.value, ast.Attribute):
                tgt = _dotted(node.value)
                if tgt:
                    aliases[name] = tgt
    aliases = {a: t for a, t in aliases.items()
               if alias_counts.get(a) == 1}

    def _canon(key: str) -> str:
        for a, full in aliases.items():
            if key == a or key.startswith(a + "[") \
                    or key.startswith(a + "."):
                return full + key[len(a):]
        return key

    # collect (donated_expr_repr, call_lineno)
    events: List[Tuple[str, int]] = []
    rebinds: List[Tuple[str, int]] = []
    loads: List[Tuple[str, int]] = []
    for node in index._own_nodes(fi):
        if isinstance(node, ast.Call):
            r = index.resolve_call(fi, node, la, lt)
            if r in targets:
                for pos in targets[r]:
                    if pos < len(node.args):
                        key = _expr_key(node.args[pos])
                        if key:
                            events.append((_canon(key), node.lineno))
        if isinstance(node, ast.Assign):
            # the rebind takes effect where the VALUE is produced, not
            # where the (possibly earlier-line) target list starts —
            # `(used, dev, out) = kernel(used, dev, x)` spans lines
            rl = getattr(node.value, "lineno", node.lineno)
            for t in node.targets:
                for key in _target_keys(t):
                    rebinds.append((_canon(key), rl))
        if isinstance(node, (ast.Name, ast.Subscript, ast.Attribute)) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            key = _expr_key(node)
            if key:
                loads.append((_canon(key), node.lineno))
    for key, cline in events:
        rebind_line = min((ln for k, ln in rebinds
                           if k == key and ln >= cline),
                          default=None)
        # a bare donated NAME is also dead through subscript/attribute
        # reads (`carry[0]` / `carry.shape` after donating `carry` —
        # the wave-loop carry shape)
        bare = "[" not in key and "." not in key

        def _hits(k):
            return k == key or (bare and (k.startswith(key + "[")
                                          or k.startswith(key + ".")))

        for k, ln in loads:
            if not _hits(k) or ln <= cline:
                continue
            if rebind_line is not None and ln >= rebind_line:
                continue
            findings.append(Finding(
                rule, fi.module, fi.qual, key, fi.path, ln,
                f"`{key}` is read after being passed at a donated "
                f"position on line {cline}; the buffer is dead once "
                "the call dispatches",
                hint="use the call's RESULT (donation returns the "
                     "updated buffer) or drop donate_argnums"))
            break
    return findings


def _target_keys(t) -> List[str]:
    """Assign-target expression keys, recursing through tuple/list
    (and starred) targets — the chunked scan-of-vmap carry rebind
    shape: the lane kernel returns the donated usage carry as the
    leading elements of a flat result tuple, so
    `(self._used, self._dev_used, out, ...) = _lane_stream_kernel(...)`
    rebinds BOTH donated buffers in one statement.  Before this, only
    single-target assigns registered as rebinds and the idiomatic
    carry-threading call site false-positived as a dead read."""
    if isinstance(t, (ast.Tuple, ast.List)):
        keys: List[str] = []
        for e in t.elts:
            keys.extend(_target_keys(e))
        return keys
    if isinstance(t, ast.Starred):
        return _target_keys(t.value)
    key = _expr_key(t)
    return [key] if key else []


def _expr_key(node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript):
        base = _dotted(node.value)
        if base and isinstance(node.slice, ast.Constant):
            return f"{base}[{node.slice.value!r}]"
    d = _dotted(node)
    return d
