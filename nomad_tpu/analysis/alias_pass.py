"""Pass 5: buffer aliasing and donation lifetime (ALIAS5xx).

Two shipped bugs motivate this pass. PR-5 found that on the CPU
backend `jax.device_put(arr)` can alias the numpy buffer ZERO-COPY:
a host-side in-place update of the template array then leaks into the
device carry and double-charges usage, depending on nothing more than
heap alignment (`ResidentSolver._put_node` now copies first — this
pass keeps it that way). PR-4's donated-carry bug read a buffer that
had already been passed at a `donate_argnums` position of a dispatch
two wrapper layers down — one hop deeper than JIT204's wrapper scan
can see.

Rules
  ALIAS501  host in-place mutation of a buffer that previously flowed
            into `device_put` WITHOUT a copy (`np.asarray`, dtype
            casts and slicing are identity-preserving and do not
            count). Checked order-sensitively within a function and
            order-insensitively across the methods of a class (the
            put-in-__init__ / mutate-in-apply shape).
  ALIAS502  read of a buffer after it was passed into a TRANSITIVELY
            donating call chain — the dataflow donation fixpoint
            follows parameter positions through any number of wrapper
            layers, subsuming and sharpening JIT204 (which stays for
            the direct/one-hop cases; ALIAS502 reports only what
            JIT204 cannot see, so nothing is double-reported).
  ALIAS503  `self.<attr> = device_put(<parameter>)` without a copy:
            the caller retains a live handle to the exact buffer now
            aliased by long-lived device state. Nothing mutates it
            *in this package*, but the contract is one caller `+=`
            away from the ALIAS501 double-charge.  (warn tier)
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisConfig, Finding, PackageIndex
from .dataflow import DataflowEngine
from .jit_pass import _check_donated_reads, find_jit_roots


def run_alias_pass(index: PackageIndex, cfg: AnalysisConfig,
                   engine: Optional[DataflowEngine] = None,
                   prior: Sequence[Finding] = ()) -> List[Finding]:
    engine = engine or DataflowEngine(index, cfg)
    findings: List[Finding] = []
    findings += _alias501_local(index, engine)
    findings += _alias501_class(index, engine)
    findings += _alias503(index, engine)
    findings += _alias502(index, cfg, engine, prior)
    return findings


# ------------------------------------------------------------ ALIAS501
def _alias501_local(index: PackageIndex,
                    engine: DataflowEngine) -> List[Finding]:
    """Within one function, in source order: device_put of an uncopied
    buffer, then an in-place mutation of the same buffer."""
    findings: List[Finding] = []
    for fkey, fi in sorted(index.functions.items()):
        fl = engine.flow(fkey, bound_cls=_own_class(index, fi))
        if not fl.puts or not fl.mutations:
            continue
        for put in fl.puts:
            if put.src.copied or not (put.src.atoms or put.src.key):
                continue
            for mut in fl.mutations:
                if mut.line <= put.line:
                    continue
                if _same_buffer(put.src.atoms, put.src.key,
                                mut.target.atoms, mut.target.key):
                    sym = put.src.key or sorted(put.src.atoms)[0]
                    findings.append(Finding(
                        "ALIAS501", fi.module, fi.qual, sym, fi.path,
                        mut.line,
                        f"in-place mutation of `{sym}` after it flowed "
                        f"into device_put on line {put.line} without a "
                        "copy; on the CPU backend device_put can alias "
                        "the numpy buffer zero-copy, so the device "
                        "carry sees the host write too (the PR-5 "
                        "usage double-charge)",
                        hint="device_put(np.array(x)) — copy before "
                             "placing — or stop mutating the host "
                             "buffer after shipping it"))
                    break
    return findings


def _alias501_class(index: PackageIndex,
                    engine: DataflowEngine) -> List[Finding]:
    """Across the methods of one concrete class: some method ships
    `self.<a>` (or a buffer it aliases) uncopied, another mutates it
    in place."""
    findings: List[Finding] = []
    seen: Set[str] = set()
    for ckey in sorted(index.classes):
        facts = engine.class_facts(ckey)
        for attr, fact in sorted(facts.items()):
            if not fact.uncopied_puts or not fact.mutations:
                continue
            put_fkey, put_line = fact.uncopied_puts[0]
            for mut_fkey, mut_line, desc in fact.mutations:
                if mut_fkey == put_fkey and mut_line <= put_line:
                    continue     # already covered order-sensitively
                mfi = index.functions[mut_fkey]
                key = f"{mut_fkey}:{mut_line}:{attr}"
                if key in seen:
                    continue
                seen.add(key)
                pfi = index.functions[put_fkey]
                findings.append(Finding(
                    "ALIAS501", mfi.module, mfi.qual, attr, mfi.path,
                    mut_line,
                    f"in-place mutation ({desc}) of `self.{attr}`, "
                    "which flows into device_put without a copy in "
                    f"{pfi.qual} ({pfi.path}:{put_line}); through a "
                    "zero-copy alias the device carry sees both the "
                    "host write and the device scatter",
                    hint="copy at the device_put site "
                         "(device_put(np.array(...))) or make the "
                         "host update produce a fresh array"))
                break
    return findings


def _same_buffer(atoms_a, key_a, atoms_b, key_b) -> bool:
    if atoms_a & atoms_b:
        return True
    if key_a and key_b:
        return (key_a == key_b or key_b.startswith(key_a + "[")
                or key_a.startswith(key_b + "["))
    return False


def _own_class(index: PackageIndex, fi) -> Optional[str]:
    return f"{fi.module}:{fi.cls}" if fi.cls else None


# ------------------------------------------------------------ ALIAS503
def _alias503(index: PackageIndex,
              engine: DataflowEngine) -> List[Finding]:
    findings: List[Finding] = []
    for fkey, fi in sorted(index.functions.items()):
        fl = engine.flow(fkey, bound_cls=_own_class(index, fi))
        for put in fl.puts:
            if put.stored_attr is None or put.src.copied:
                continue
            params = sorted(a[6:] for a in put.src.atoms
                            if a.startswith("param:"))
            if not params:
                continue
            findings.append(Finding(
                "ALIAS503", fi.module, fi.qual, put.stored_attr,
                fi.path, put.line,
                f"`self.{put.stored_attr}` aliases caller-owned buffer "
                f"`{params[0]}` through an uncopied device_put; the "
                "caller keeps a live handle to device-resident state",
                hint="device_put(np.array(...)) to sever the alias at "
                     "the boundary"))
    return findings


# ------------------------------------------------------------ ALIAS502
def _alias502(index: PackageIndex, cfg: AnalysisConfig,
              engine: DataflowEngine,
              prior: Sequence[Finding]) -> List[Finding]:
    donation = engine.donation_map()
    if not donation:
        return []
    # what JIT204 already covers: direct donating roots and their
    # one-hop wrappers (jit_pass's wrapper scan)
    direct: Dict[str, Tuple[int, ...]] = {}
    for r in find_jit_roots(index):
        if r.donate:
            direct[r.fkey] = r.donate
    one_hop: Set[str] = set()
    for fkey, fi in index.functions.items():
        if fi.parent is None and index.callees(fkey) & set(direct):
            one_hop.add(fkey)
    prior_sites = {(f.path, f.line, f.symbol) for f in prior
                   if f.rule == "JIT204"}

    findings: List[Finding] = []
    for fkey, fi in sorted(index.functions.items()):
        callees = index.callees(fkey)
        targets = {c: donation[c] for c in callees
                   if c in donation and c not in direct
                   and c not in one_hop}
        if not targets:
            continue
        for f in _check_donated_reads(index, fi, targets,
                                      rule="ALIAS502"):
            if (f.path, f.line, f.symbol) in prior_sites:
                continue
            findings.append(Finding(
                f.rule, f.module, f.func, f.symbol, f.path, f.line,
                f.message + " (donation reaches this call through a "
                "multi-hop wrapper chain the direct JIT204 scan "
                "cannot see)",
                hint=f.hint))
    return findings
