"""Package index + module-level call graph for nomadlint.

Everything here is pure `ast` over source text: no module in the
analyzed package is ever imported, so the analyzer runs in environments
without JAX, a device, or the package's optional deps.

Resolution is deliberately conservative name/alias/annotation
propagation — enough to follow the call chains the three passes care
about (apply handlers -> store mutators, jit roots -> traced helpers,
`self.attr` method dispatch through constructor-assigned or
annotation-typed attributes) without attempting full type inference.
Unresolvable calls are kept as dotted external names so deny-list
checks (time.*, random.*, ...) still see them.
"""
from __future__ import annotations

import ast
import dataclasses
import difflib
import fnmatch
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# severity tiers: "error" findings gate CI (exit 1); "warn" findings
# are advisory heuristics (exit 3 when they are the only findings).
# Everything not listed here is an error.
WARN_RULES = frozenset({"LOCK302", "SHARD403", "ALIAS503", "OBS802",
                        "RACE903"})

# rule-id prefix -> pass name (used by --json/by_pass and bench's
# lint_summary so BENCH_DETAIL records per-pass lint state)
RULE_PASSES: Tuple[Tuple[str, str], ...] = (
    ("FSM", "fsm"), ("JIT", "jit"), ("LOCK", "lock"),
    ("SHARD", "shard"), ("ALIAS", "alias"), ("SCORE", "score"),
    ("ROBUST", "robust"), ("OBS", "obs"), ("RACE", "race"),
)

# rules whose id prefix belongs to another pass: LOCK305 is produced by
# the lockset race pass (it needs the interprocedural held-set fixpoint
# the syntactic lock pass doesn't compute)
_RULE_PASS_OVERRIDES = {"LOCK305": "race"}


def severity_of(rule: str) -> str:
    return "warn" if rule in WARN_RULES else "error"


def pass_of(rule: str) -> str:
    if rule in _RULE_PASS_OVERRIDES:
        return _RULE_PASS_OVERRIDES[rule]
    for prefix, name in RULE_PASSES:
        if rule.startswith(prefix):
            return name
    return "other"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str           # e.g. "FSM101"
    module: str         # dotted module ("nomad_tpu.state.store")
    func: str           # qualname within module ("Class.method", "f.inner")
    symbol: str         # the offending name (baseline-key component)
    path: str           # file path (repo-relative where possible)
    line: int
    message: str
    hint: str = ""

    @property
    def key(self) -> str:
        """Line-number-free identity used by baseline suppressions, so
        unrelated edits don't invalidate entries."""
        return f"{self.rule}:{self.module}:{self.func}:{self.symbol}"

    @property
    def severity(self) -> str:
        return severity_of(self.rule)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: {self.rule} [{self.module}:{self.func}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclasses.dataclass
class Report:
    version: str
    findings: List[Finding]          # unsuppressed
    suppressed: List[Finding]
    stale_baseline_keys: List[str]   # baseline entries matching nothing
    # stale key -> nearest current finding key (rename forensics: a
    # mid-PR file/function rename silently strands baseline entries;
    # the nearest miss names the probable new spelling)
    stale_suggestions: Dict[str, str] = dataclasses.field(
        default_factory=dict)

    @classmethod
    def build(cls, findings: Sequence[Finding], baseline,
              version: str) -> "Report":
        if baseline is None:
            return cls(version, list(findings), [], [])
        kept, supp = [], []
        used: Set[str] = set()
        for f in findings:
            if baseline.matches(f.key):
                supp.append(f)
                used.add(baseline.match_key(f.key))
            else:
                kept.append(f)
        stale = [k for k in baseline.keys() if k not in used]
        all_keys = sorted({f.key for f in findings})
        suggestions: Dict[str, str] = {}
        for k in stale:
            near = difflib.get_close_matches(k, all_keys, n=1,
                                             cutoff=0.5)
            if near:
                suggestions[k] = near[0]
        return cls(version, kept, supp, stale, suggestions)

    def counts_by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def counts_by_pass(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            p = pass_of(f.rule)
            out[p] = out.get(p, 0) + 1
        return dict(sorted(out.items()))

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warn"]

    @property
    def ok(self) -> bool:
        return not self.findings


@dataclasses.dataclass
class AnalysisConfig:
    """Pass configuration; tests point these at synthetic fixture
    packages."""
    # FSM pass: glob patterns over "module:qualname" naming the raft
    # apply roots, and the (module, class) of the replicated store.
    fsm_roots: Tuple[str, ...] = (
        "nomad_tpu.raft.fsm:StateFSM.apply",
        "nomad_tpu.raft.fsm:StateFSM._ap_*",
        "nomad_tpu.raft.fsm:StateFSM.restore",
    )
    store_module: str = "nomad_tpu.state.store"
    store_class: str = "StateStore"
    # Lock pass scope: the threaded server plane. Attr-write/read
    # discipline is only enforced for modules under these prefixes;
    # module-global mutation (LOCK303) is package-wide.
    lock_module_prefixes: Tuple[str, ...] = (
        "nomad_tpu.server", "nomad_tpu.state", "nomad_tpu.rpc",
        "nomad_tpu.raft", "nomad_tpu.solver",
    )
    # SHARD401: scatter helpers whose jit body is built dynamically
    # (defeating call resolution), as "module:qualname@param_pos" —
    # passing a NamedSharding-sharded operand at that position outside
    # shard_map is the GSPMD double-apply hazard.
    scatter_helpers: Tuple[str, ...] = (
        "nomad_tpu.solver.kernel:delta_scatter_set@0",
        "nomad_tpu.solver.kernel:delta_scatter_add@0",
    )
    # SCORE6xx: override of the scoring-site registry (None = the
    # package registry in score_pass.DEFAULT_SCORER_SITES); tests
    # point this at synthetic fixture backends.
    scorer_sites: Optional[Tuple] = None
    # ROBUST701 scope: recovery-critical planes where a swallowed
    # exception turns an injected fault into silent state divergence.
    robust_module_prefixes: Tuple[str, ...] = (
        "nomad_tpu.raft", "nomad_tpu.rpc", "nomad_tpu.server",
        "nomad_tpu.parallel", "nomad_tpu.solver",
    )
    # OBS8xx: metric/series name hygiene.  Names must be lowercase
    # dotted paths whose first segment (the namespace) is registered
    # here; dynamically-built names are cardinality hazards (OBS802,
    # warn) that carry a baseline justification naming the bound.
    obs_metric_prefixes: Tuple[str, ...] = (
        "broker", "coordinator", "health", "mesh", "metrics", "plan",
        "rpc", "scheduler", "serving", "slo", "solver", "telemetry",
        "watchdog", "worker",
    )
    # the sinks themselves (name arrives as a parameter there)
    obs_exclude_modules: Tuple[str, ...] = (
        "nomad_tpu.utils.metrics", "nomad_tpu.telemetry.series",
    )
    # RACE9xx / LOCK305 scope: the planes whose thread-shared classes
    # get Eraser-style guarded-by inference and blocking-under-lock
    # checks (the scale-out control plane plus everything it locks).
    race_module_prefixes: Tuple[str, ...] = (
        "nomad_tpu.server", "nomad_tpu.state", "nomad_tpu.rpc",
        "nomad_tpu.raft", "nomad_tpu.solver",
        "nomad_tpu.scheduler.fleet",
    )
    # LOCK305: package functions that block BY CONTRACT (device solve,
    # store index waits, raft proposal round-trips, RPC) — calling one
    # with a hot-path lock held is an error even when the blocking op
    # itself hides behind a resolution boundary.  fnmatch patterns
    # over "module:qualname".
    blocking_roots: Tuple[str, ...] = (
        "nomad_tpu.solver.solve:*.solve",
        "nomad_tpu.solver.resident:*.solve*",
        "nomad_tpu.state.store:*.wait_for_index",
        "nomad_tpu.state.store:*.wait_for_change",
        "nomad_tpu.raft.node:RaftNode.propose*",
        "nomad_tpu.rpc.client:RpcClient.call",
        "nomad_tpu.rpc.transport:*.call",
        "nomad_tpu.rpc.wire:send_frame",
        "nomad_tpu.rpc.wire:recv_frame",
        "nomad_tpu.scheduler.fleet:process_fleet",
        "nomad_tpu.scheduler.fleet:SolveCoordinator.submit",
        # pipelined hot path (ISSUE 19): the fetch/future-wait entry
        # points block until the DEVICE finishes a round — holding a
        # hot-path lock across one serializes every other worker behind
        # the solve, exactly the stall the async split exists to remove.
        "nomad_tpu.solver.resident:*.finish_stream",
        "nomad_tpu.solver.solve:PendingSolve.wait",
        "nomad_tpu.scheduler.fleet:fleet_finish",
        "nomad_tpu.scheduler.fleet:SolveCoordinator.submit_nowait",
    )


class FuncInfo:
    __slots__ = ("key", "module", "qual", "cls", "node", "path",
                 "nested", "parent")

    def __init__(self, key: str, module: str, qual: str,
                 cls: Optional[str], node: ast.AST, path: str,
                 parent: Optional[str]):
        self.key = key            # "module:qual"
        self.module = module
        self.qual = qual
        self.cls = cls            # enclosing class name, if a method
        self.node = node
        self.path = path
        self.nested: List[str] = []   # keys of directly nested defs
        self.parent = parent

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]


class ClassInfo:
    __slots__ = ("key", "module", "name", "node", "bases", "methods",
                 "attr_types", "attr_elem_types", "path")

    def __init__(self, key: str, module: str, name: str,
                 node: ast.ClassDef, path: str):
        self.key = key            # "module:Class"
        self.module = module
        self.name = name
        self.node = node
        self.path = path
        self.bases: List[str] = []          # resolved class keys
        self.methods: Dict[str, str] = {}   # name -> func key
        self.attr_types: Dict[str, str] = {}  # self attr -> class key
        # self attr -> ELEMENT class key for list-of-instances attrs
        # (`self._shards = [_Shard(...) for ...]`) — the sharded-
        # container composition edge (ISSUE 17)
        self.attr_elem_types: Dict[str, str] = {}


class ModuleInfo:
    __slots__ = ("name", "path", "tree", "aliases", "globals")

    def __init__(self, name: str, path: str, tree: ast.Module):
        self.name = name
        self.path = path
        self.tree = tree
        # import alias -> dotted target ("_time" -> "time",
        # "X" -> "nomad_tpu.structs.X")
        self.aliases: Dict[str, str] = {}
        self.globals: Set[str] = set()      # module-level assigned names


def _resolve_relative(module: str, node: ast.ImportFrom) -> str:
    """Turn `from ..a import b` inside `module` into the absolute
    source module for the import."""
    if not node.level:
        return node.module or ""
    parts = module.split(".")
    # a module's package is itself minus the last component
    base = parts[: len(parts) - node.level]
    if node.module:
        base = base + node.module.split(".")
    return ".".join(base)


def _collect_imports(module: str, body: Iterable[ast.stmt],
                     out: Dict[str, str]) -> None:
    for node in body:
        if isinstance(node, ast.Import):
            for al in node.names:
                if al.asname:
                    out[al.asname] = al.name
                else:
                    # `import a.b` binds `a`
                    head = al.name.split(".")[0]
                    out[head] = head
        elif isinstance(node, ast.ImportFrom):
            src = _resolve_relative(module, node)
            for al in node.names:
                if al.name == "*":
                    continue
                out[al.asname or al.name] = (
                    f"{src}.{al.name}" if src else al.name)


class PackageIndex:
    def __init__(self, package_name: str):
        self.package = package_name
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self._edges: Dict[str, Set[str]] = {}
        self._externals: Dict[str, List[Tuple[str, int]]] = {}

    # ------------------------------------------------------------ build
    @classmethod
    def build(cls, package_dir: str, package_name: str,
              cache_dir: Optional[str] = None) -> "PackageIndex":
        """Index the package.  `cache_dir` (opt-in, off in CI) enables
        the on-disk incremental cache: parsed ASTs are pickled per
        file, keyed by content hash, so an unchanged file never
        re-parses.  The key salts in the Python minor version — pickled
        ast nodes do not travel across interpreters — and any cache
        miss/corruption silently falls back to a fresh parse."""
        idx = cls(package_name)
        pkg_root = os.path.join(package_dir, package_name)
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
        for dirpath, dirnames, filenames in os.walk(pkg_root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, package_dir)
                mod = rel[:-3].replace(os.sep, ".")
                if mod.endswith(".__init__"):
                    mod = mod[: -len(".__init__")]
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                tree = _parse_cached(src, path, cache_dir)
                if tree is None:
                    continue
                idx._index_module(mod, rel, tree)
        idx._resolve_class_bases()
        idx._infer_attr_types()
        return idx

    def _index_module(self, mod: str, path: str, tree: ast.Module) -> None:
        mi = ModuleInfo(mod, path, tree)
        _collect_imports(mod, ast.walk(tree), mi.aliases)
        for node in tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                for t in (node.targets if isinstance(node, ast.Assign)
                          else [node.target]):
                    if isinstance(t, ast.Name):
                        mi.globals.add(t.id)
        self.modules[mod] = mi
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_func(mi, node, qual_prefix="", cls=None,
                                 parent=None)
            elif isinstance(node, ast.ClassDef):
                ckey = f"{mod}:{node.name}"
                ci = ClassInfo(ckey, mod, node.name, node, path)
                self.classes[ckey] = ci
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fkey = self._index_func(
                            mi, sub, qual_prefix=node.name + ".",
                            cls=node.name, parent=None)
                        ci.methods[sub.name] = fkey

    def _index_func(self, mi: ModuleInfo, node, qual_prefix: str,
                    cls: Optional[str], parent: Optional[str]) -> str:
        qual = qual_prefix + node.name
        key = f"{mi.name}:{qual}"
        if key in self.functions:        # same-name re-def (branch-local)
            key = f"{key}#{node.lineno}"
            qual = f"{qual}#{node.lineno}"
        fi = FuncInfo(key, mi.name, qual, cls, node, mi.path, parent)
        self.functions[key] = fi
        if parent is not None and parent in self.functions:
            self.functions[parent].nested.append(key)
        for sub in _direct_defs(node):
            self._index_func(mi, sub, qual_prefix=qual + ".",
                             cls=cls, parent=key)
        return key

    def _resolve_class_bases(self) -> None:
        for ci in self.classes.values():
            mi = self.modules[ci.module]
            for b in ci.node.bases:
                name = _dotted(b)
                if not name:
                    continue
                resolved = self._resolve_symbol(mi, name)
                if resolved and resolved in self.classes:
                    ci.bases.append(resolved)

    # ----------------------------------------------- attr type inference
    def _infer_attr_types(self) -> None:
        for ci in self.classes.values():
            mi = self.modules[ci.module]
            for mname, fkey in ci.methods.items():
                fn = self.functions[fkey].node
                ann: Dict[str, str] = {}
                for a in list(fn.args.args) + list(fn.args.kwonlyargs):
                    t = self._annotation_class(mi, a.annotation)
                    if t:
                        ann[a.arg] = t
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Assign):
                        continue
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            t = self._expr_class(mi, ann, node.value)
                            if t:
                                ci.attr_types.setdefault(tgt.attr, t)
                            et = self._elem_class(mi, ann, node.value)
                            if et:
                                ci.attr_elem_types.setdefault(tgt.attr,
                                                              et)

    def _annotation_class(self, mi: ModuleInfo, node) -> Optional[str]:
        if node is None:
            return None
        if isinstance(node, ast.Subscript):      # Optional[X], List[X]
            return self._annotation_class(mi, node.slice)
        if isinstance(node, ast.BinOp):          # X | None
            return self._annotation_class(mi, node.left)
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            try:
                return self._annotation_class(
                    mi, ast.parse(node.value, mode="eval").body)
            except SyntaxError:
                return None
        name = _dotted(node)
        if not name:
            return None
        r = self._resolve_symbol(mi, name)
        return r if r in self.classes else None

    def _elem_class(self, mi: ModuleInfo, ann: Dict[str, str],
                    node) -> Optional[str]:
        """Element class key of a list-of-instances expression —
        `[_Shard(...) for ...]` or `[Foo(), Foo()]` — if every element
        infers to the same package class."""
        if isinstance(node, ast.ListComp):
            return self._expr_class(mi, ann, node.elt)
        if isinstance(node, ast.List) and node.elts:
            ts = {self._expr_class(mi, ann, e) for e in node.elts}
            ts.discard(None)
            if len(ts) == 1:
                return ts.pop()
        return None

    def _expr_class(self, mi: ModuleInfo, ann: Dict[str, str],
                    node) -> Optional[str]:
        """Class key of an expression's value, if inferable."""
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name:
                r = self._resolve_symbol(mi, name)
                if r in self.classes:
                    return r
            return None
        if isinstance(node, ast.Name):
            return ann.get(node.id)
        if isinstance(node, ast.BoolOp):         # x = store or StateStore()
            for v in node.values:
                t = self._expr_class(mi, ann, v)
                if t:
                    return t
        if isinstance(node, ast.IfExp):
            return (self._expr_class(mi, ann, node.body)
                    or self._expr_class(mi, ann, node.orelse))
        return None

    # ------------------------------------------------------- resolution
    def _resolve_symbol(self, mi: ModuleInfo, dotted: str) -> Optional[str]:
        """Resolve a dotted name in a module to a package-internal key
        ("mod:Thing") or None."""
        head, _, rest = dotted.partition(".")
        target = mi.aliases.get(head)
        if target is None:
            # plain module-level name
            if not rest and f"{mi.name}:{dotted}" in self.functions:
                return f"{mi.name}:{dotted}"
            if not rest and f"{mi.name}:{dotted}" in self.classes:
                return f"{mi.name}:{dotted}"
            return None
        full = target + ("." + rest if rest else "")
        if not full.startswith(self.package):
            return None
        # try splitting "pkg.mod.Sym" into module + symbol
        parts = full.split(".")
        for cut in range(len(parts), 0, -1):
            mod = ".".join(parts[:cut])
            if mod in self.modules:
                sym = ".".join(parts[cut:])
                if not sym:
                    return None
                for cand in (f"{mod}:{sym}",):
                    if cand in self.functions or cand in self.classes:
                        return cand
                # one more hop: re-exported through __init__ aliases
                sub = self.modules[mod].aliases.get(parts[cut])
                if sub is not None and cut + 1 <= len(parts):
                    deeper = sub + "." + ".".join(parts[cut + 1:]) \
                        if parts[cut + 1:] else sub
                    return self._resolve_dotted_abs(deeper)
                return None
        return None

    def _resolve_dotted_abs(self, full: str) -> Optional[str]:
        parts = full.split(".")
        for cut in range(len(parts), 0, -1):
            mod = ".".join(parts[:cut])
            if mod in self.modules:
                sym = ".".join(parts[cut:])
                cand = f"{mod}:{sym}"
                if cand in self.functions or cand in self.classes:
                    return cand
        return None

    def method_on(self, class_key: str, name: str) -> Optional[str]:
        """Look a method up on a class and its (package) bases."""
        seen = set()
        stack = [class_key]
        while stack:
            ck = stack.pop(0)
            if ck in seen or ck not in self.classes:
                continue
            seen.add(ck)
            ci = self.classes[ck]
            if name in ci.methods:
                return ci.methods[name]
            stack.extend(ci.bases)
        return None

    def class_of_func(self, fi: FuncInfo) -> Optional[ClassInfo]:
        if fi.cls is None:
            return None
        return self.classes.get(f"{fi.module}:{fi.cls}")

    def _local_imports(self, fi: FuncInfo) -> Dict[str, str]:
        cache = getattr(self, "_li_cache", None)
        if cache is None:
            cache = self._li_cache = {}
        out = cache.get(fi.key)
        if out is None:
            out = {}
            _collect_imports(fi.module, ast.walk(fi.node), out)
            cache[fi.key] = out
        return out

    def _param_annotations(self, fi: FuncInfo) -> Dict[str, str]:
        mi = self.modules[fi.module]
        out: Dict[str, str] = {}
        args = fi.node.args
        for a in list(args.args) + list(args.kwonlyargs):
            t = self._annotation_class(mi, a.annotation)
            if t:
                out[a.arg] = t
        return out

    def _local_var_types(self, fi: FuncInfo) -> Dict[str, str]:
        """Single-pass local inference: `x = Cls(...)` / annotated
        params / loop vars and subscripts over self-attr containers
        with a known element class (`for s in self._shards:` /
        `s = self._shards[i]`).  The container cases keep the call
        graph honest for the fan-out-over-helpers shape: a single
        watcher thread iterating a list of shard objects is a call
        edge into the shard class, and thread-rootset propagation
        (race pass) depends on seeing it."""
        cache = getattr(self, "_lvt_cache", None)
        if cache is None:
            cache = self._lvt_cache = {}
        cached = cache.get(fi.key)
        if cached is not None:
            return cached
        mi = self.modules[fi.module]
        ci = self.class_of_func(fi)
        ann = self._param_annotations(fi)
        out = dict(ann)
        for node in ast.walk(fi.node):
            tgt = val = None
            elem_only = False
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, val = node.targets[0].id, node.value
            elif isinstance(node, ast.For):
                it = node.iter
                if isinstance(it, ast.Call) \
                        and isinstance(it.func, ast.Name) \
                        and it.func.id == "enumerate" and it.args:
                    it = it.args[0]
                    if isinstance(node.target, ast.Tuple) \
                            and len(node.target.elts) == 2 \
                            and isinstance(node.target.elts[1], ast.Name):
                        tgt = node.target.elts[1].id
                elif isinstance(node.target, ast.Name):
                    tgt = node.target.id
                val, elem_only = it, True
            if tgt is None or val is None:
                continue
            t = None if elem_only else self._expr_class(mi, ann, val)
            if t is None and ci is not None:
                base = val.value if isinstance(val, ast.Subscript) else \
                    (val if elem_only else None)
                if isinstance(base, ast.Attribute) \
                        and isinstance(base.value, ast.Name) \
                        and base.value.id == "self":
                    t = self._attr_elem_type(ci, base.attr)
            if t:
                out.setdefault(tgt, t)
        cache[fi.key] = out
        return out

    def resolve_call(self, fi: FuncInfo, call: ast.Call,
                     local_aliases: Optional[Dict[str, str]] = None,
                     local_types: Optional[Dict[str, str]] = None
                     ) -> Optional[str]:
        """Internal func key a call resolves to, or None."""
        mi = self.modules[fi.module]
        fnode = call.func
        ci = self.class_of_func(fi)
        if isinstance(fnode, ast.Name):
            # nested def in the enclosing scope chain
            cur: Optional[FuncInfo] = fi
            while cur is not None:
                for nk in cur.nested:
                    if self.functions[nk].name == fnode.id:
                        return nk
                cur = (self.functions.get(cur.parent)
                       if cur.parent else None)
            if local_aliases and fnode.id in local_aliases:
                full = local_aliases[fnode.id]
                if full.startswith(self.package):
                    r = self._resolve_dotted_abs(full)
                    if r:
                        return self._callable_target(r)
            r = self._resolve_symbol(mi, fnode.id)
            if r:
                return self._callable_target(r)
            return None
        if isinstance(fnode, ast.Attribute):
            base = fnode.value
            meth = fnode.attr
            # self.m()
            if isinstance(base, ast.Name) and base.id == "self" and ci:
                return self.method_on(ci.key, meth)
            # self.attr.m()
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self" and ci):
                t = self._attr_type(ci, base.attr)
                if t:
                    return self.method_on(t, meth)
                return None
            # self.attr[i].m() — container of known element class
            if (isinstance(base, ast.Subscript)
                    and isinstance(base.value, ast.Attribute)
                    and isinstance(base.value.value, ast.Name)
                    and base.value.value.id == "self" and ci):
                t = self._attr_elem_type(ci, base.value.attr)
                if t:
                    return self.method_on(t, meth)
                return None
            # var.m() / alias.m() / alias.sub.m()
            name = _dotted(fnode)
            if name:
                head = name.split(".")[0]
                if local_types and head in local_types and "." not in \
                        name[len(head) + 1:]:
                    return self.method_on(local_types[head], meth)
                for amap in (local_aliases or {}, mi.aliases):
                    if head in amap:
                        full = amap[head] + name[len(head):]
                        if full.startswith(self.package):
                            r = self._resolve_dotted_abs(full)
                            if r:
                                return self._callable_target(r)
                        return None
        return None

    def _attr_type(self, ci: ClassInfo, attr: str) -> Optional[str]:
        seen = set()
        stack = [ci.key]
        while stack:
            ck = stack.pop(0)
            if ck in seen or ck not in self.classes:
                continue
            seen.add(ck)
            c = self.classes[ck]
            if attr in c.attr_types:
                return c.attr_types[attr]
            stack.extend(c.bases)
        return None

    def _attr_elem_type(self, ci: ClassInfo, attr: str) -> Optional[str]:
        """Element class of a self-attr container (mro walk), mirroring
        `_attr_type` for `attr_elem_types`."""
        seen = set()
        stack = [ci.key]
        while stack:
            ck = stack.pop(0)
            if ck in seen or ck not in self.classes:
                continue
            seen.add(ck)
            c = self.classes[ck]
            if attr in c.attr_elem_types:
                return c.attr_elem_types[attr]
            stack.extend(c.bases)
        return None

    def _callable_target(self, key: str) -> Optional[str]:
        if key in self.functions:
            return key
        if key in self.classes:                 # instantiation
            return self.method_on(key, "__init__")
        return None

    # ------------------------------------------------------- call graph
    def callees(self, fkey: str) -> Set[str]:
        cached = self._edges.get(fkey)
        if cached is not None:
            return cached
        fi = self.functions[fkey]
        la = self._local_imports(fi)
        lt = self._local_var_types(fi)
        out: Set[str] = set(fi.nested)   # tracing/threads run nested defs
        for node in self._own_nodes(fi):
            if isinstance(node, ast.Call):
                r = self.resolve_call(fi, node, la, lt)
                if r:
                    out.add(r)
        self._edges[fkey] = out
        return out

    def _own_nodes(self, fi: FuncInfo):
        """Walk a function body EXCLUDING nested function/class bodies
        (nested defs have their own FuncInfo)."""
        stack: List[ast.AST] = [fi.node]
        while stack:
            node = stack.pop()
            if node is not fi.node and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def external_calls(self, fkey: str) -> List[Tuple[str, int]]:
        """(dotted-name, lineno) for every call whose base resolves
        outside the package (through import aliases), plus builtins."""
        cached = self._externals.get(fkey)
        if cached is not None:
            return cached
        fi = self.functions[fkey]
        mi = self.modules[fi.module]
        la = self._local_imports(fi)
        out: List[Tuple[str, int]] = []
        for node in self._own_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if not name:
                continue
            head, _, rest = name.partition(".")
            target = la.get(head) or mi.aliases.get(head)
            if target is not None:
                full = target + ("." + rest if rest else "")
                if not full.startswith(self.package):
                    out.append((full, node.lineno))
            elif "." not in name and f"{mi.name}:{name}" not in \
                    self.functions and f"{mi.name}:{name}" not in \
                    self.classes:
                out.append((name, node.lineno))   # builtin-ish
        self._externals[fkey] = out
        return out

    def reachable(self, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            stack.extend(self.callees(k) - seen)
        return seen

    def match_funcs(self, patterns: Sequence[str]) -> List[str]:
        out = []
        for k in self.functions:
            base = k.split("#")[0]
            if any(fnmatch.fnmatchcase(base, p) for p in patterns):
                out.append(k)
        return sorted(out)


def _parse_cached(src: str, path: str,
                  cache_dir: Optional[str]) -> Optional[ast.Module]:
    """ast.parse with an optional content-hash-keyed pickle cache."""
    if not cache_dir:
        try:
            return ast.parse(src, filename=path)
        except SyntaxError:
            return None
    import hashlib
    import pickle
    import sys
    salt = f"py{sys.version_info[0]}.{sys.version_info[1]}|"
    digest = hashlib.sha256(
        (salt + src).encode("utf-8")).hexdigest()
    cpath = os.path.join(cache_dir, digest + ".ast.pkl")
    try:
        with open(cpath, "rb") as f:
            tree = pickle.load(f)
        if isinstance(tree, ast.Module):
            return tree
    except (OSError, pickle.PickleError, EOFError, AttributeError,
            ValueError):
        pass
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return None
    try:
        tmp = cpath + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(tree, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, cpath)
    except OSError:
        pass
    return tree


def _direct_defs(node) -> List[ast.AST]:
    """Function defs DIRECTLY nested in `node`'s body (not inside a
    deeper def/class)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(n)
            continue
        if isinstance(n, ast.ClassDef):
            continue
        stack.extend(ast.iter_child_nodes(n))
    out.sort(key=lambda n: n.lineno)
    return out


def _dotted(node) -> Optional[str]:
    """a.b.c -> "a.b.c" for pure Name/Attribute chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def with_lock_names(node: ast.With) -> List[str]:
    """Lock-ish names acquired by a with statement: `with self._lock:`
    -> "self._lock", `with _CACHE_LOCK:` -> "_CACHE_LOCK"."""
    out = []
    for item in node.items:
        d = _dotted(item.context_expr)
        if d:
            out.append(d)
        elif isinstance(item.context_expr, ast.Call):
            d = _dotted(item.context_expr.func)
            if d:
                out.append(d)
    return out
