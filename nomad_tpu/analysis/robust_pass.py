"""Pass 7: swallowed exceptions in recovery-critical modules.

The chaos plane (ISSUE 14) injects faults precisely where this repo's
recovery code runs: raft step-down, rpc transport, broker redelivery,
shard fail/recover, solver failover.  A `except: pass` or a broad
`except Exception` that discards the error in those modules converts
an injected (or real) fault into silent state divergence — the exact
class of bug the invariant harness exists to catch, found here
statically instead.

Rules
  ROBUST701  bare `except:` or broad `except Exception/BaseException`
             whose handler discards the error: no re-raise, no
             reference to the bound exception, and no logging/metrics/
             event call in the handler body

Scope is `AnalysisConfig.robust_module_prefixes` (default: the raft,
rpc, server, parallel and solver planes).  Narrow handlers
(`except OSError: pass` around a socket close) are deliberate cleanup
idiom and are never flagged — only bare/Exception/BaseException
catches.  A handler "handles" the error if it re-raises, references
the bound name (wrapping, storing, returning it), or calls anything
logging-shaped (dotted path containing log/warn/error/exc/debug/
info/print/record/trace/metric/incr/event/fail/abort).
"""
from __future__ import annotations

import ast
from typing import List, Optional

from .core import AnalysisConfig, Finding, PackageIndex, _dotted

BROAD_TYPES = ("Exception", "BaseException")

#: substrings of a dotted call path that count as surfacing the error
_SURFACING_TOKENS = ("log", "warn", "error", "exc", "debug", "info",
                     "print", "record", "trace", "metric", "incr",
                     "event", "fail", "abort")


def _broad_caught(h: ast.ExceptHandler) -> Optional[str]:
    """The broad type name a handler catches, or None if narrow."""
    if h.type is None:
        return "bare"
    types = (h.type.elts if isinstance(h.type, ast.Tuple)
             else [h.type])
    for t in types:
        d = _dotted(t)
        if d and d.split(".")[-1] in BROAD_TYPES:
            return d.split(".")[-1]
    return None


def _handles_error(h: ast.ExceptHandler) -> bool:
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return True
        if (h.name and isinstance(node, ast.Name)
                and node.id == h.name
                and isinstance(node.ctx, ast.Load)):
            return True
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and any(tok in d.lower()
                         for tok in _SURFACING_TOKENS):
                return True
    return False


def run_robust_pass(index: PackageIndex,
                    cfg: AnalysisConfig) -> List[Finding]:
    findings: List[Finding] = []
    prefixes = cfg.robust_module_prefixes
    for fkey, fi in sorted(index.functions.items()):
        if not fi.module.startswith(prefixes):
            continue
        for node in index._own_nodes(fi):
            if not isinstance(node, ast.Try):
                continue
            for h in node.handlers:
                etype = _broad_caught(h)
                if etype is None or _handles_error(h):
                    continue
                what = ("bare except" if etype == "bare"
                        else f"except {etype}")
                findings.append(Finding(
                    rule="ROBUST701", module=fi.module, func=fi.qual,
                    symbol=etype, path=fi.path, line=h.lineno,
                    message=(f"{what} swallows the error in a "
                             f"recovery-critical module: no re-raise, "
                             f"no use of the bound exception, no "
                             f"logging/metrics call in the handler"),
                    hint=("narrow the except, re-raise, or surface "
                          "the error (bind it and log/count it); if "
                          "the drop is deliberate, baseline with a "
                          "justification")))
    return findings
