"""Pass 9: interprocedural lockset race detection (Eraser-style).

Go's upstream Nomad keeps its concurrent server plane honest with
`go test -race`; Python has no race sanitizer, so this pass is ours.
Where the syntactic LOCK pass checks one function at a time, this pass
computes, per call site, the set of locks *statically held* — tracking
`with self._lock:` regions and acquire()/release() pairs through
`_locked`-convention helpers and arbitrary call depth via a fixpoint
over the package call graph — then runs guarded-by inference over
every shared attribute reachable from two or more thread roots.

Machinery
  * canonical lock ids: `Class.attr` for instance locks (Condition
    objects wrapping a lock — `threading.Condition(self._lock)` —
    collapse onto the wrapped lock's id so `with self._cv:` counts as
    holding `self._lock`), `module:name` for module-level locks;
  * entry-lockset fixpoint: thread roots and public entry points pin
    to the empty set, `*_locked` helpers pin to their class's main
    lock (the convention IS the contract), everything else starts at ⊤
    and intersects `held_at(call site) ∪ entry(caller)` over all known
    callers until stable;
  * thread roots: `threading.Thread(target=...)` / `threading.Timer`
    targets, executor `.submit`/`.map` first arguments that resolve
    into the package, and `run()` of `threading.Thread` subclasses;
    one synthetic "external" root covers the public API surface of
    thread-shared classes (any client thread may call in);
  * guarded-by inference: for each shared `self.attr` of an in-scope
    thread-shared class, intersect held-lock sets over its WRITES
    (unguarded reads stay LOCK302's domain).

Rules
  RACE901  shared attribute written with an empty guard intersection
           across ≥2 thread roots (error)
  RACE902  inconsistent guard: every write is locked, but no common
           lock exists — the sharded-broker hazard class (error)
  RACE903  check-then-act: a guarded read is released before the
           dependent guarded write re-acquires the same lock (warn)
  LOCK305  blocking call (device solve, fsync, RPC, Future.result /
           Event.wait, blocking queue.get, thread join) reached while
           a hot-path lock is held (error)

Known limits (documented in STATIC_ANALYSIS.md): lock identity is
attr-name-based (two instances of a class share one static id — right
for per-shard discipline, blind to instance aliasing); LOCK305 is not
fully transitive (the entry fixpoint carries context into callees, and
call sites into known-blocking callees are checked, but a blocking op
two resolution failures away is missed); guarded-by inference is
writes-only and skips `__init__` (construction happens-before
publication).
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .core import (AnalysisConfig, Finding, FuncInfo, PackageIndex,
                   _dotted)
from .lock_pass import (LOCK_FACTORIES, _end, _module_locks,
                        _self_attr_write, _thread_shared_classes)

# attrs assigned one of these hold synchronization primitives, not
# shared data — they are excluded from guarded-by inference
SYNC_FACTORIES = LOCK_FACTORIES + (
    "threading.Event", "threading.Barrier", "queue.Queue",
    "queue.SimpleQueue", "queue.LifoQueue", "queue.PriorityQueue",
)

# container-method calls that mutate the receiver in place:
# `self.pending.append(x)` is a WRITE to self.pending.  "set" is
# deliberately absent (Event.set() would drown the signal).
MUTATORS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
})

# external calls that block by contract
BLOCKING_EXTERNALS = frozenset({
    "time.sleep", "os.fsync", "os.fdatasync", "select.select",
    "socket.create_connection", "subprocess.run",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen",
})

# method names that block: Future.result, Event/Condition.wait,
# Thread.join, socket recv/accept.  `.join` needs the timeout-shaped
# argument check below to stay clear of str.join.
BLOCKING_METHODS = frozenset({"result", "wait", "join", "recv",
                              "accept"})

_EXTERNAL_ROOT = "external"


def _in_scope(module: str, cfg: AnalysisConfig) -> bool:
    return any(module == p or module.startswith(p + ".")
               for p in cfg.race_module_prefixes)


class _Facts:
    """Per-function lock facts: with-region spans, explicit
    acquire/release events, resolved internal call sites."""
    __slots__ = ("spans", "events", "calls")

    def __init__(self):
        self.spans: List[Tuple[int, int, str]] = []   # (a, b, lock id)
        self.events: List[Tuple[int, str, int]] = []  # (line, id, ±1)
        self.calls: List[Tuple[int, str]] = []        # (line, fkey)


class _Engine:
    def __init__(self, index: PackageIndex, cfg: AnalysisConfig):
        self.index = index
        self.cfg = cfg
        self._facts_cache: Dict[str, _Facts] = {}
        self._held_cache: Dict[Tuple[str, int], FrozenSet[str]] = {}
        self._locks_cache: Dict[str, Dict[str, str]] = {}
        self._sync_cache: Dict[str, Dict[str, str]] = {}
        self._ltypes_cache: Dict[str, Dict[str, str]] = {}
        self._modlocks_cache: Dict[str, Set[str]] = {}
        self.entry: Dict[str, Optional[FrozenSet[str]]] = {}
        self.rootsets: Dict[str, Set[str]] = {}
        # (class key, attr) -> inferred guard (non-empty write
        # intersection); the lockdep runtime witness cross-checks this
        self.guards: Dict[Tuple[str, str], FrozenSet[str]] = {}

    # ------------------------------------------------ lock identities
    def _sync_attrs(self, ck: str) -> Dict[str, str]:
        """self attrs assigned a sync primitive (class + package
        bases): attr -> full factory name."""
        cached = self._sync_cache.get(ck)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        stack, seen = [ck], set()
        while stack:
            c = stack.pop()
            if c in seen or c not in self.index.classes:
                continue
            seen.add(c)
            ci = self.index.classes[c]
            mi = self.index.modules[ci.module]
            for fkey in ci.methods.values():
                fi = self.index.functions[fkey]
                for node in self.index._own_nodes(fi):
                    if not (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)):
                        continue
                    d = _dotted(node.value.func)
                    if not d:
                        continue
                    head = d.split(".")[0]
                    full = (mi.aliases.get(head) or head) + d[len(head):]
                    if full not in SYNC_FACTORIES:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and isinstance(
                                t.value, ast.Name) \
                                and t.value.id == "self":
                            out.setdefault(t.attr, full)
            stack.extend(ci.bases)
        self._sync_cache[ck] = out
        return out

    def _class_locks(self, ck: str) -> Dict[str, str]:
        """attr -> canonical lock id ("Class.rep") for lock-ish attrs,
        Condition-wraps-lock alias groups collapsed onto the wrapped
        attr so `with self._cv:` and `with self._lock:` unify."""
        cached = self._locks_cache.get(ck)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        if ck not in self.index.classes:
            self._locks_cache[ck] = out
            return out
        cname = self.index.classes[ck].name
        own: Set[str] = set()
        alias: Dict[str, str] = {}
        stack, seen = [ck], set()
        while stack:
            c = stack.pop()
            if c in seen or c not in self.index.classes:
                continue
            seen.add(c)
            ci = self.index.classes[c]
            mi = self.index.modules[ci.module]
            for fkey in ci.methods.values():
                fi = self.index.functions[fkey]
                for node in self.index._own_nodes(fi):
                    if not (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)):
                        continue
                    d = _dotted(node.value.func)
                    if not d:
                        continue
                    head = d.split(".")[0]
                    full = (mi.aliases.get(head) or head) + d[len(head):]
                    if full not in LOCK_FACTORIES:
                        continue
                    for t in node.targets:
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            continue
                        if full == "threading.Condition" \
                                and node.value.args:
                            ad = _dotted(node.value.args[0])
                            if ad and ad.startswith("self."):
                                alias.setdefault(t.attr, ad[5:])
                                continue
                        own.add(t.attr)
            stack.extend(ci.bases)
        for a in own:
            out[a] = f"{cname}.{a}"
        for a, tgt in alias.items():
            rep, hops = tgt, 0
            while rep in alias and hops < 5:
                rep, hops = alias[rep], hops + 1
            out[a] = f"{cname}.{rep}" if rep in own else f"{cname}.{a}"
        self._locks_cache[ck] = out
        return out

    def _mod_locks(self, module: str) -> Set[str]:
        cached = self._modlocks_cache.get(module)
        if cached is None:
            cached = _module_locks(self.index, module)
            self._modlocks_cache[module] = cached
        return cached

    # -------------------------------------------------- local typing
    def _ltypes(self, fi: FuncInfo) -> Dict[str, str]:
        """core's local var types, extended with shard-element and
        self-attr hops: `sh = self._shards[i]`, `for sh in
        self._shards:`, `st = self._store`."""
        cached = self._ltypes_cache.get(fi.key)
        if cached is not None:
            return cached
        lt = dict(self.index._local_var_types(fi))
        ci = self.index.class_of_func(fi)
        if ci is not None:
            for node in self.index._own_nodes(fi):
                tgt = val = None
                elem_only = False
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    tgt, val = node.targets[0].id, node.value
                elif isinstance(node, ast.For):
                    it = node.iter
                    # for sh in self._shards: / enumerate(self._shards)
                    if isinstance(it, ast.Call) and isinstance(
                            it.func, ast.Name) \
                            and it.func.id == "enumerate" and it.args:
                        it = it.args[0]
                        if isinstance(node.target, ast.Tuple) and len(
                                node.target.elts) == 2 and isinstance(
                                node.target.elts[1], ast.Name):
                            tgt = node.target.elts[1].id
                    elif isinstance(node.target, ast.Name):
                        tgt = node.target.id
                    val, elem_only = it, True
                if tgt is None or val is None:
                    continue
                t = None
                if isinstance(val, ast.Subscript):
                    base = val.value
                    if isinstance(base, ast.Attribute) and isinstance(
                            base.value, ast.Name) \
                            and base.value.id == "self":
                        t = self._elem_type(ci.key, base.attr)
                elif isinstance(val, ast.Attribute) and isinstance(
                        val.value, ast.Name) and val.value.id == "self":
                    t = (self._elem_type(ci.key, val.attr) if elem_only
                         else self.index._attr_type(ci, val.attr))
                if t:
                    lt.setdefault(tgt, t)
        self._ltypes_cache[fi.key] = lt
        return lt

    def _elem_type(self, ck: str, attr: str) -> Optional[str]:
        stack, seen = [ck], set()
        while stack:
            c = stack.pop()
            if c in seen or c not in self.index.classes:
                continue
            seen.add(c)
            ci = self.index.classes[c]
            if attr in ci.attr_elem_types:
                return ci.attr_elem_types[attr]
            stack.extend(ci.bases)
        return None

    # ----------------------------------------------- lock resolution
    def _lock_id_of_expr(self, fi: FuncInfo, node) -> Optional[str]:
        """Canonical lock id of an expression used as a lock (with-
        item, acquire receiver), or None."""
        ci = self.index.class_of_func(fi)
        # self.cont[i].X — the per-shard form _dotted can't render
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Subscript):
            base = node.value.value
            if isinstance(base, ast.Attribute) and isinstance(
                    base.value, ast.Name) and base.value.id == "self" \
                    and ci is not None:
                ek = self._elem_type(ci.key, base.attr)
                if ek:
                    return self._class_locks(ek).get(node.attr)
            return None
        d = _dotted(node)
        if d is None:
            return None
        parts = d.split(".")
        if parts[0] == "self" and ci is not None:
            if len(parts) == 2:
                return self._class_locks(ci.key).get(parts[1])
            if len(parts) == 3:
                t = self.index._attr_type(ci, parts[1])
                if t:
                    return self._class_locks(t).get(parts[2])
            return None
        if len(parts) == 1:
            if d in self._mod_locks(fi.module):
                return f"{fi.module}:{d}"
            return None
        if len(parts) == 2:
            lt = self._ltypes(fi)
            if parts[0] in lt:
                return self._class_locks(lt[parts[0]]).get(parts[1])
        return None

    # ------------------------------------------------ per-func facts
    def _facts(self, fkey: str) -> _Facts:
        cached = self._facts_cache.get(fkey)
        if cached is not None:
            return cached
        fi = self.index.functions[fkey]
        la = self.index._local_imports(fi)
        lt = self._ltypes(fi)
        f = _Facts()
        for node in self.index._own_nodes(fi):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = self._lock_id_of_expr(fi, item.context_expr)
                    if lid:
                        f.spans.append((node.lineno, _end(node), lid))
            elif isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) \
                        and fn.attr in ("acquire", "release"):
                    lid = self._lock_id_of_expr(fi, fn.value)
                    if lid:
                        f.events.append(
                            (node.lineno, lid,
                             1 if fn.attr == "acquire" else -1))
                r = self.index.resolve_call(fi, node, la, lt)
                if r:
                    f.calls.append((node.lineno, r))
        f.events.sort()
        f.calls.sort()
        self._facts_cache[fkey] = f
        return f

    def _held_at(self, fkey: str, line: int) -> FrozenSet[str]:
        cached = self._held_cache.get((fkey, line))
        if cached is not None:
            return cached
        f = self._facts(fkey)
        held = {lid for (a, b, lid) in f.spans if a <= line <= b}
        bal: Dict[str, int] = {}
        for (ln, lid, d) in f.events:
            if ln < line:
                bal[lid] = bal.get(lid, 0) + d
        held.update(lid for lid, n in bal.items() if n > 0)
        out = frozenset(held)
        self._held_cache[(fkey, line)] = out
        return out

    # ------------------------------------------------- thread roots
    def _resolve_ref(self, fi: FuncInfo, node) -> Optional[str]:
        """Function key a non-call reference resolves to (thread
        targets, executor submissions)."""
        ci = self.index.class_of_func(fi)
        mi = self.index.modules[fi.module]
        if isinstance(node, ast.Name):
            cur: Optional[FuncInfo] = fi
            while cur is not None:
                for nk in cur.nested:
                    if self.index.functions[nk].name == node.id:
                        return nk
                cur = (self.index.functions.get(cur.parent)
                       if cur.parent else None)
            r = self.index._resolve_symbol(mi, node.id)
            if r:
                return self.index._callable_target(r)
            la = self.index._local_imports(fi)
            if node.id in la and la[node.id].startswith(
                    self.index.package):
                r = self.index._resolve_dotted_abs(la[node.id])
                if r:
                    return self.index._callable_target(r)
            return None
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if not d:
                return None
            parts = d.split(".")
            if parts[0] == "self" and ci is not None:
                if len(parts) == 2:
                    return self.index.method_on(ci.key, parts[1])
                if len(parts) == 3:
                    t = self.index._attr_type(ci, parts[1])
                    if t:
                        return self.index.method_on(t, parts[2])
                return None
            if len(parts) == 2:
                lt = self._ltypes(fi)
                if parts[0] in lt:
                    return self.index.method_on(lt[parts[0]], parts[1])
        return None

    def _thread_roots(self) -> Set[str]:
        roots: Set[str] = set()
        for fkey, fi in self.index.functions.items():
            mi = self.index.modules[fi.module]
            la = self.index._local_imports(fi)
            for node in self.index._own_nodes(fi):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                full = None
                if d:
                    head = d.split(".")[0]
                    tgt = la.get(head) or mi.aliases.get(head)
                    if tgt:
                        full = tgt + d[len(head):]
                if full == "threading.Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            r = self._resolve_ref(fi, kw.value)
                            if r:
                                roots.add(r)
                elif full == "threading.Timer":
                    texpr = None
                    for kw in node.keywords:
                        if kw.arg == "function":
                            texpr = kw.value
                    if texpr is None and len(node.args) >= 2:
                        texpr = node.args[1]
                    if texpr is not None:
                        r = self._resolve_ref(fi, texpr)
                        if r:
                            roots.add(r)
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("submit", "map") \
                        and node.args:
                    r = self._resolve_ref(fi, node.args[0])
                    if r:
                        roots.add(r)
        # run() of threading.Thread subclasses starts as its own thread
        for ck, ci in self.index.classes.items():
            mi = self.index.modules[ci.module]
            for b in ci.node.bases:
                bd = _dotted(b)
                if not bd:
                    continue
                head = bd.split(".")[0]
                full = (mi.aliases.get(head) or head) + bd[len(head):]
                if full == "threading.Thread" and "run" in ci.methods:
                    roots.add(ci.methods["run"])
        return roots

    def _compute_rootsets(self, roots: Set[str],
                          scope_shared: Set[str]) -> None:
        rs: Dict[str, Set[str]] = {}
        for rk in sorted(roots):
            for f in self.index.reachable({rk}):
                rs.setdefault(f, set()).add(rk)
        # the synthetic external root: any client thread may enter a
        # thread-shared class through its public surface
        ext: List[str] = []
        for ck in sorted(scope_shared):
            ci = self.index.classes[ck]
            for mname, fkey in ci.methods.items():
                if mname.startswith("_") or fkey in roots:
                    continue
                ext.append(fkey)
        for f in self.index.reachable(ext):
            rs.setdefault(f, set()).add(_EXTERNAL_ROOT)
        self.rootsets = rs

    # ------------------------------------------- entry-set fixpoint
    def _pin(self, fkey: str, fi: FuncInfo,
             roots: Set[str]) -> Optional[FrozenSet[str]]:
        if fkey in roots:
            return frozenset()
        name = fi.name
        if name.endswith("_locked"):
            # the suffix IS the contract: the caller holds the class's
            # main lock.  Prefer `_lock`, else every class lock (a
            # multi-lock class using the convention holds them all or
            # names its helpers more precisely).
            ci = self.index.class_of_func(fi)
            if ci is not None:
                locks = self._class_locks(ci.key)
                if locks:
                    main = locks.get("_lock")
                    if main:
                        return frozenset({main})
                    return frozenset(set(locks.values()))
            return frozenset()
        if not name.startswith("_"):
            # public entry: callable lock-free from anywhere
            return frozenset()
        return None

    def _compute_entries(self, roots: Set[str]) -> None:
        callers: Dict[str, List[Tuple[str, int]]] = {}
        for fkey in self.index.functions:
            for (line, callee) in self._facts(fkey).calls:
                callers.setdefault(callee, []).append((fkey, line))
        entry: Dict[str, Optional[FrozenSet[str]]] = {}
        pinned: Set[str] = set()
        for fkey, fi in self.index.functions.items():
            p = self._pin(fkey, fi, roots)
            entry[fkey] = p
            if p is not None:
                pinned.add(fkey)
        for _ in range(64):
            changed = False
            for callee, sites in callers.items():
                if callee in pinned or callee not in entry:
                    continue
                acc: Optional[FrozenSet[str]] = None
                for (ck, line) in sites:
                    ce = entry.get(ck)
                    if ce is None:
                        continue          # ⊤ caller: no information
                    s = self._held_at(ck, line) | ce
                    acc = s if acc is None else (acc & s)
                if acc is not None and acc != entry[callee]:
                    entry[callee] = acc
                    changed = True
            if not changed:
                break
        self.entry = entry

    # ---------------------------------------------- access analysis
    def _mutator_write(self, node) -> Optional[Tuple[str, int]]:
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) \
                and node.func.attr in MUTATORS:
            base = node.func.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) and isinstance(
                    base.value, ast.Name) and base.value.id == "self":
                return base.attr, node.lineno
        return None

    def _collect_accesses(self, scope_shared: Set[str]) -> Dict[
            Tuple[str, str],
            List[Tuple[str, int, bool, Optional[FrozenSet[str]]]]]:
        """(class key, attr) -> [(fkey, line, is_write, lockset)].
        lockset None means the method's entry context is unknown (⊤:
        never called from a resolved site) — excluded from inference.
        """
        acc: Dict[Tuple[str, str],
                  List[Tuple[str, int, bool,
                             Optional[FrozenSet[str]]]]] = {}
        for ck in sorted(scope_shared):
            ci = self.index.classes[ck]
            sync = set(self._sync_attrs(ck))
            for mname, fkey in sorted(ci.methods.items()):
                if mname == "__init__":
                    continue
                if not self.rootsets.get(fkey):
                    continue
                fi = self.index.functions[fkey]
                ent = self.entry.get(fkey)
                for node in self.index._own_nodes(fi):
                    pairs: List[Tuple[str, int, bool]] = []
                    w = _self_attr_write(node)
                    if w:
                        pairs.append((w[0], w[1], True))
                    mw = self._mutator_write(node)
                    if mw:
                        pairs.append((mw[0], mw[1], True))
                    if isinstance(node, ast.Attribute) and isinstance(
                            node.ctx, ast.Load) and isinstance(
                            node.value, ast.Name) \
                            and node.value.id == "self":
                        pairs.append((node.attr, node.lineno, False))
                    for (attr, line, isw) in pairs:
                        if attr in sync:
                            continue
                        if self.index.method_on(ck, attr):
                            continue     # bound-method ref, not data
                        ls = (None if ent is None
                              else self._held_at(fkey, line) | ent)
                        acc.setdefault((ck, attr), []).append(
                            (fkey, line, isw, ls))
        return acc

    # ------------------------------------------------------- rules
    def run(self, prior=()) -> List[Finding]:
        findings: List[Finding] = []
        roots = self._thread_roots()
        self._compute_entries(roots)
        shared = _thread_shared_classes(self.index)
        scope_shared = {
            ck for ck in shared
            if ck in self.index.classes
            and _in_scope(self.index.classes[ck].module, self.cfg)}
        self._compute_rootsets(roots, scope_shared)
        accesses = self._collect_accesses(scope_shared)
        findings += self._guard_inference(accesses, prior)
        findings += self._check_then_act(accesses)
        findings += self._blocking_under_lock(scope_shared, roots)
        return findings

    def _guard_inference(self, accesses, prior) -> List[Finding]:
        findings: List[Finding] = []
        prior301 = {(f.module, f.func.split(".")[0], f.symbol)
                    for f in prior if f.rule == "LOCK301"}
        for (ck, attr), accs in sorted(accesses.items()):
            ci = self.index.classes[ck]
            roots_here: Set[str] = set()
            for (fkey, _line, _w, _ls) in accs:
                roots_here |= self.rootsets.get(fkey, set())
            if len(roots_here) < 2:
                continue
            writes = [(fk, ln, ls) for (fk, ln, w, ls) in accs
                      if w and ls is not None]
            if not writes:
                continue
            inter: Optional[FrozenSet[str]] = None
            for (_fk, _ln, ls) in writes:
                inter = ls if inter is None else (inter & ls)
            if inter:
                self.guards[(ck, attr)] = inter
                continue
            unguarded = sorted(
                (ln, fk) for (fk, ln, ls) in writes if not ls)
            if unguarded:
                if (ci.module, ci.name, attr) in prior301:
                    continue            # LOCK301 already owns this one
                line, fk = unguarded[0]
                fi = self.index.functions[fk]
                findings.append(Finding(
                    "RACE901", ci.module, fi.qual, attr, ci.path, line,
                    f"shared `self.{attr}` of {ci.name} is written "
                    "with no lock held; its accesses are reachable "
                    f"from {len(roots_here)} thread roots and the "
                    "guard intersection over writes is empty",
                    hint="guard every write with the owning lock, or "
                         "baseline with the happens-before argument "
                         "that makes the write safe"))
            else:
                locks_seen = sorted(
                    {lid for (_fk, _ln, ls) in writes for lid in ls})
                line, fk = min((ln, fk) for (fk, ln, _ls) in writes)
                fi = self.index.functions[fk]
                findings.append(Finding(
                    "RACE902", ci.module, fi.qual, attr, ci.path, line,
                    f"`self.{attr}` of {ci.name} is guarded "
                    "inconsistently: every write holds a lock but no "
                    "common one exists "
                    f"({', '.join(locks_seen)})",
                    hint="pick ONE lock to own the attribute; "
                         "inconsistent guards protect nothing"))
        return findings

    def _check_then_act(self, accesses) -> List[Finding]:
        """RACE903: within one method (directly, or through a same-
        class callee), a read of a multi-root attribute under lock L in
        one region and a dependent write under L in a LATER, disjoint
        region — the lock was dropped between check and act."""
        findings: List[Finding] = []
        # multi-root attrs with at least one write
        multi: Dict[Tuple[str, str], List] = {}
        writers: Dict[Tuple[str, str],
                      List[Tuple[str, FrozenSet[str]]]] = {}
        for (ck, attr), accs in accesses.items():
            roots_here: Set[str] = set()
            for (fk, _ln, _w, _ls) in accs:
                roots_here |= self.rootsets.get(fk, set())
            if len(roots_here) < 2 or not any(w for (_f, _l, w, _s)
                                              in accs):
                continue
            multi[(ck, attr)] = accs
            for (fk, _ln, w, ls) in accs:
                if w and ls:
                    writers.setdefault((ck, attr), []).append((fk, ls))
        done: Set[Tuple[str, str]] = set()
        for (ck, attr), accs in sorted(multi.items()):
            ci = self.index.classes[ck]
            by_func: Dict[str, List[Tuple[int, bool]]] = {}
            for (fk, ln, w, _ls) in accs:
                by_func.setdefault(fk, []).append((ln, w))
            for fk in sorted(by_func):
                if (fk, attr) in done:
                    continue
                fi = self.index.functions[fk]
                spans = self._facts(fk).spans
                reads = [(ln, a, b, lid) for (ln, w) in by_func[fk]
                         if not w
                         for (a, b, lid) in spans if a <= ln <= b]
                if not reads:
                    continue
                hit = self._ctamatch(ck, attr, fk, by_func[fk], reads,
                                     spans, writers)
                if hit is not None:
                    line, desc = hit
                    findings.append(Finding(
                        "RACE903", ci.module, fi.qual, attr, fi.path,
                        line,
                        f"check-then-act on `self.{attr}`: {desc} — "
                        "the state checked can change while the lock "
                        "is dropped",
                        hint="restructure so the check and the act "
                             "share one lock hold (a `*_locked` "
                             "helper keeps the pass informed)"))
                    done.add((fk, attr))
        return findings

    def _ctamatch(self, ck, attr, fk, accs, reads, spans, writers):
        # (a) direct: read under L in span S1, write under the same L
        # in a later disjoint span S2 of the same method
        for (rln, ra, rb, rlid) in reads:
            for (wln, w) in accs:
                if not w or wln <= rb:
                    continue
                for (wa, wb, wlid) in spans:
                    if wlid == rlid and wa <= wln <= wb and wa > rb:
                        return (wln,
                                f"read under {rlid} (line {rln}), "
                                f"lock released, write re-acquires it "
                                f"(line {wln})")
        # (b) call-mediated: read under L, then a later call made with
        # L NOT held into a same-class method that writes attr under L
        fi = self.index.functions[fk]
        ent = self.entry.get(fk) or frozenset()
        class_meths = set(self.index.classes[ck].methods.values())
        for (rln, ra, rb, rlid) in reads:
            for (cln, callee) in self._facts(fk).calls:
                if cln <= rb or callee == fk:
                    continue
                if callee not in class_meths:
                    continue
                if rlid in (self._held_at(fk, cln) | ent):
                    continue             # still held: no window
                for (wfk, wls) in writers.get((ck, attr), ()):
                    if wfk == callee and rlid in wls:
                        cq = self.index.functions[callee].qual
                        return (cln,
                                f"read under {rlid} (line {rln}), "
                                f"then `{cq}` re-acquires it for the "
                                f"dependent write (call at line {cln})")
        return None

    # --------------------------------------------- LOCK305 blocking
    def _direct_blocking(self) -> Dict[str, List[Tuple[int, str,
                                                       Optional[str]]]]:
        """fkey -> [(line, symbol, receiver lock id or None)] for ops
        that block by contract, regardless of lock state."""
        out: Dict[str, List[Tuple[int, str, Optional[str]]]] = {}
        for fkey, fi in self.index.functions.items():
            ops: List[Tuple[int, str, Optional[str]]] = []
            for (name, line) in self.index.external_calls(fkey):
                if name in BLOCKING_EXTERNALS:
                    ops.append((line, name, None))
            mi = self.index.modules[fi.module]
            la = self.index._local_imports(fi)
            ci = self.index.class_of_func(fi)
            qattrs = {a for a, fac in
                      (self._sync_attrs(ci.key) if ci else {}).items()
                      if fac.startswith("queue.")}
            for node in self.index._own_nodes(fi):
                if not (isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute)):
                    continue
                meth = node.func.attr
                recv = node.func.value
                d = _dotted(recv)
                if meth in BLOCKING_METHODS:
                    if d is None:
                        continue        # literal receiver: str.join etc
                    head = d.split(".")[0]
                    tgt = la.get(head) or mi.aliases.get(head)
                    if tgt and not tgt.startswith(self.index.package):
                        continue        # os.path.join, shutil.move...
                    if meth == "join" and not _timeout_shaped(node):
                        continue        # separator.join(parts)
                    lid = self._lock_id_of_expr(fi, recv)
                    ops.append((line_of(node), f"{d}.{meth}",
                                lid if meth == "wait" else None))
                elif meth == "get" and d and d.startswith("self.") \
                        and d[5:] in qattrs:
                    if any(kw.arg == "block" and isinstance(
                            kw.value, ast.Constant)
                            and kw.value.value is False
                            for kw in node.keywords):
                        continue
                    ops.append((line_of(node), f"{d}.get", None))
            if ops:
                out[fkey] = sorted(ops)
        return out

    def _blocking_under_lock(self, scope_shared: Set[str],
                             roots: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        hot: Set[str] = set()
        for ck in scope_shared:
            hot |= set(self._class_locks(ck).values())
        for mod in self.index.modules:
            if _in_scope(mod, self.cfg):
                hot |= {f"{mod}:{n}" for n in self._mod_locks(mod)}
        direct = self._direct_blocking()
        blocking = set(direct) | set(
            self.index.match_funcs(list(self.cfg.blocking_roots)))
        seen: Set[Tuple[str, str]] = set()
        for fkey, fi in sorted(self.index.functions.items()):
            if not _in_scope(fi.module, self.cfg):
                continue
            ent = self.entry.get(fkey) or frozenset()
            for (line, symbol, recv_lock) in direct.get(fkey, ()):
                held = self._held_at(fkey, line) | ent
                hh = held & hot
                if recv_lock:
                    # Condition.wait releases its OWN lock while parked
                    hh = hh - {recv_lock}
                if not hh:
                    continue
                key = (fkey, symbol)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(self._b305(fi, line, symbol, hh))
            for (line, callee) in self._facts(fkey).calls:
                if callee == fkey or callee not in blocking:
                    continue
                held = self._held_at(fkey, line) | ent
                hh = (held & hot) - (self.entry.get(callee)
                                     or frozenset())
                if not hh:
                    continue            # the callee's own frame reports
                sym = self.index.functions[callee].qual
                key = (fkey, sym)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(self._b305(fi, line, sym, hh,
                                           via_call=True))
        return findings

    def _b305(self, fi: FuncInfo, line: int, symbol: str,
              held: Set[str], via_call: bool = False) -> Finding:
        what = ("call into blocking" if via_call else "blocking call")
        return Finding(
            "LOCK305", fi.module, fi.qual, symbol, fi.path, line,
            f"{what} `{symbol}` while holding "
            f"{', '.join(sorted(held))}; a hot-path lock held across "
            "a solve/fsync/RPC/wait stalls every thread contending it",
            hint="move the blocking op outside the critical section "
                 "(snapshot under the lock, block after release), or "
                 "baseline with the durability/ordering argument that "
                 "requires it")


def _timeout_shaped(call: ast.Call) -> bool:
    """`t.join()` / `t.join(5.0)` / `t.join(timeout=...)` — excludes
    the one-iterable str.join form."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    if not call.args:
        return not call.keywords
    if len(call.args) == 1:
        a = call.args[0]
        if isinstance(a, ast.Constant) and isinstance(
                a.value, (int, float)):
            return True
        if isinstance(a, ast.Name) and a.id in ("timeout", "deadline",
                                                "remain", "wait_s"):
            return True
    return False


def line_of(node) -> int:
    return getattr(node, "lineno", 0)


def run_race_pass(index: PackageIndex, cfg: AnalysisConfig,
                  prior=()) -> List[Finding]:
    return _Engine(index, cfg).run(prior)


def infer_guards(index: PackageIndex, cfg: AnalysisConfig
                 ) -> Dict[Tuple[str, str], FrozenSet[str]]:
    """Static guarded-by map for the lockdep runtime witness:
    (class key, attr) -> the non-empty lock-id intersection over all
    writes.  `utils.lockdep` cross-checks recorded runtime held-sets
    against this: static says guarded ⇒ the storm never saw an
    unguarded access."""
    eng = _Engine(index, cfg)
    eng.run(())
    return dict(eng.guards)
