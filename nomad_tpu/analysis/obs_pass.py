"""Pass 8: observability hygiene — metric & series name discipline.

The telemetry plane (ISSUE 15) gives every subsystem three sinks: the
MetricsRegistry (counters/gauges/samples/histograms), the
TimeSeriesStore (multi-resolution rings) and the Prometheus
exposition derived from both.  All three key on dotted metric names,
and two classes of naming bugs are invisible at runtime until a
dashboard breaks:

  * a malformed or unregistered name ("WorkerLatency", "foo") lands in
    the JSON dump but mangles unpredictably in Prometheus and never
    joins its subsystem's namespace — dashboards silently miss it;
  * a name built from runtime data (f-string over an eval id, a queue
    name, an exception type) is an unbounded-cardinality hazard: the
    registry's per-namespace cap absorbs the storm, but every key it
    sheds is a metric an operator expected to see.

Rules
  OBS801  (error) literal metric/series name that is not a lowercase
          dotted path, or whose namespace (first dot-segment) is not
          in the registered-prefix set
  OBS802  (warn)  dynamically-built metric/series name — bounded-
          cardinality sites are fine but must say so in the baseline

Sites checked: calls to the registry methods (incr_counter /
set_gauge / add_sample / measure_since / observe_hist / timed) on any
receiver, and `record(...)` calls whose receiver resolves to the
telemetry series store.  The registries themselves (where the name is
a parameter) are excluded via `AnalysisConfig.obs_exclude_modules`.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .core import AnalysisConfig, Finding, PackageIndex, _dotted

#: MetricsRegistry entry points whose first argument is a metric name
METRIC_METHODS = frozenset({
    "incr_counter", "set_gauge", "add_sample", "measure_since",
    "observe_hist", "timed"})

#: name-expr keyword spellings across the two sinks
_NAME_KWARGS = ("key", "name")

#: lowercase dotted path: at least two segments, [a-z0-9_] characters
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")


def _series_receiver(index: PackageIndex, fi, call: ast.Call) -> bool:
    """True when a `record(...)` call's receiver is (or aliases) the
    telemetry series store — so job/event `record` methods elsewhere
    never enter the pass."""
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = _dotted(call.func.value)
    if not recv:
        return False
    head = recv.split(".")[0]
    if "series" in recv:
        return True
    la = index._local_imports(fi)
    mi = index.modules[fi.module]
    target = la.get(head) or mi.aliases.get(head)
    return bool(target and "telemetry.series" in target)


def _name_expr(call: ast.Call) -> Optional[ast.expr]:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg in _NAME_KWARGS:
            return kw.value
    return None


def _fstring_pattern(node: ast.JoinedStr) -> str:
    """Reconstruct an f-string as a pattern: literal runs kept,
    interpolations collapsed to `*` — readable, stable baseline keys
    ("broker.deliveries.*", "*.burn_*")."""
    out: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            out.append(v.value)
        else:
            out.append("*")
    return "".join(out)


def _check_literal(name: str, prefixes: Tuple[str, ...]
                   ) -> Optional[str]:
    """OBS801 message for a literal name, or None when clean."""
    if not _NAME_RE.match(name):
        return (f"metric name {name!r} is not a lowercase dotted "
                f"path (expected e.g. 'worker.solve_latency_s')")
    ns = name.split(".", 1)[0]
    if ns not in prefixes:
        return (f"metric namespace {ns!r} is not registered "
                f"(known: {', '.join(prefixes)})")
    return None


def run_obs_pass(index: PackageIndex,
                 cfg: AnalysisConfig) -> List[Finding]:
    findings: List[Finding] = []
    prefixes = cfg.obs_metric_prefixes
    for fkey, fi in sorted(index.functions.items()):
        if fi.module in cfg.obs_exclude_modules:
            continue
        for node in index._own_nodes(fi):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            if meth in METRIC_METHODS:
                pass
            elif meth == "record" and _series_receiver(index, fi, node):
                pass
            else:
                continue
            expr = _name_expr(node)
            if expr is None:
                continue
            if isinstance(expr, ast.Constant):
                if not isinstance(expr.value, str):
                    continue
                msg = _check_literal(expr.value, prefixes)
                if msg:
                    findings.append(Finding(
                        rule="OBS801", module=fi.module, func=fi.qual,
                        symbol=expr.value, path=fi.path,
                        line=node.lineno, message=msg,
                        hint=("use a lowercase dotted name under a "
                              "registered namespace, or register the "
                              "new namespace in "
                              "AnalysisConfig.obs_metric_prefixes")))
                continue
            if isinstance(expr, ast.JoinedStr):
                pattern = _fstring_pattern(expr)
                ns = pattern.split(".", 1)[0]
                if "." in pattern and "*" not in ns \
                        and ns not in prefixes:
                    findings.append(Finding(
                        rule="OBS801", module=fi.module, func=fi.qual,
                        symbol=pattern, path=fi.path,
                        line=node.lineno,
                        message=(f"metric namespace {ns!r} is not "
                                 f"registered (known: "
                                 f"{', '.join(prefixes)})"),
                        hint=("register the namespace in "
                              "AnalysisConfig.obs_metric_prefixes")))
                symbol = pattern
            else:
                symbol = "<dynamic>"
            findings.append(Finding(
                rule="OBS802", module=fi.module, func=fi.qual,
                symbol=symbol, path=fi.path, line=node.lineno,
                message=(f"metric name {symbol!r} is built at runtime "
                         f"— unbounded cardinality grows the registry "
                         f"until the namespace cap sheds keys"),
                hint=("fold runtime values into label-free names or "
                      "bound the value set; if cardinality is "
                      "provably bounded, baseline with the bound as "
                      "justification")))
    return findings
