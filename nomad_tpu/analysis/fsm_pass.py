"""Pass 1: FSM determinism.

The raft FSM (StateFSM.apply -> state store mutators) must produce
bit-identical state on every replica from (index, payload, prior
state). Anything nondeterministic inside that call graph — wall-clock
reads, randomness, hash-order iteration feeding writes — silently forks
replicas; and any StateStore mutation reachable from OUTSIDE the apply
path bypasses the raft log entirely (a write that exists on one server
only).

Rules
  FSM101  wall-clock read reachable from the apply path
  FSM102  randomness reachable from the apply path
  FSM103  iteration over an unordered set feeding logic in an
          apply-reachable function (Python set order varies with
          PYTHONHASHSEED across replica processes)
  FSM104  StateStore mutator called from outside the apply path
"""
from __future__ import annotations

import ast
from typing import List, Set

from .core import AnalysisConfig, Finding, PackageIndex, _dotted

WALL_CLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.clock_gettime",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today", "time.localtime",
    "time.gmtime",
}
RANDOM_EXACT = {"uuid.uuid1", "uuid.uuid4", "os.urandom", "os.getrandom"}
RANDOM_PREFIXES = ("random.", "secrets.", "numpy.random.", "np.random.",
                   "jax.random.")


def _is_wall_clock(name: str) -> bool:
    return name in WALL_CLOCK


def _is_random(name: str) -> bool:
    return (name in RANDOM_EXACT
            or any(name.startswith(p) for p in RANDOM_PREFIXES))


def _set_producing(node, set_vars: Set[str]) -> bool:
    """Does this expression produce a plain `set` (unordered)?"""
    if isinstance(node, (ast.SetComp, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d in ("set", "frozenset"):
            return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Sub, ast.BitOr, ast.BitAnd, ast.BitXor)):
        # keys() - keys() and friends are set algebra
        for side in (node.left, node.right):
            if isinstance(side, ast.Call):
                d = _dotted(side.func)
                if d and d.endswith(".keys"):
                    return True
            if _set_producing(side, set_vars):
                return True
    if isinstance(node, ast.Name):
        return node.id in set_vars
    return False


def _sorted_wrapped(node) -> bool:
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        return d in ("sorted", "list.sort", "min", "max", "sum", "len",
                     "frozenset")
    return False


def run_fsm_pass(index: PackageIndex, cfg: AnalysisConfig
                 ) -> List[Finding]:
    findings: List[Finding] = []
    roots = index.match_funcs(list(cfg.fsm_roots))
    reach = index.reachable(roots)

    # ---- FSM101/102: nondeterministic leaf calls in the apply closure
    for fkey in sorted(reach):
        fi = index.functions[fkey]
        for name, lineno in index.external_calls(fkey):
            if _is_wall_clock(name):
                findings.append(Finding(
                    "FSM101", fi.module, fi.qual, name, fi.path, lineno,
                    f"wall-clock read `{name}` is reachable from the "
                    "raft apply path; replicas applying the same log "
                    "entry would diverge",
                    hint="carry the timestamp in the raft log entry "
                         "payload (stamped by the proposer) and pass "
                         "it down"))
            elif _is_random(name):
                findings.append(Finding(
                    "FSM102", fi.module, fi.qual, name, fi.path, lineno,
                    f"randomness `{name}` is reachable from the raft "
                    "apply path; replicas would diverge",
                    hint="generate ids/choices on the proposer and "
                         "ship them in the log entry payload"))

    # ---- FSM103: unordered-set iteration inside the apply closure
    for fkey in sorted(reach):
        fi = index.functions[fkey]
        set_vars: Set[str] = set()
        # first sweep: locals assigned from set-producing expressions
        for node in index._own_nodes(fi):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                if _set_producing(node.value, set_vars):
                    set_vars.add(node.targets[0].id)
        for node in index._own_nodes(fi):
            if not isinstance(node, ast.For):
                continue
            it = node.iter
            if _sorted_wrapped(it):
                continue
            if _set_producing(it, set_vars):
                sym = (it.id if isinstance(it, ast.Name)
                       else type(it).__name__)
                findings.append(Finding(
                    "FSM103", fi.module, fi.qual, f"for:{sym}",
                    fi.path, node.lineno,
                    "iteration over an unordered set in an "
                    "apply-reachable function; set order varies with "
                    "PYTHONHASHSEED across replica processes",
                    hint="wrap the iterable in sorted(...) so every "
                         "replica visits elements in the same order"))

    # ---- FSM104: store mutators called from outside the apply path
    store_ck = f"{cfg.store_module}:{cfg.store_class}"
    mutators = _store_mutators(index, store_ck)
    exempt_modules = {cfg.store_module} | {
        r.split(":")[0] for r in cfg.fsm_roots}
    for fkey, fi in sorted(index.functions.items()):
        if fkey in reach or fi.module in exempt_modules:
            continue
        if not (index.callees(fkey) & mutators):
            continue
        la = index._local_imports(fi)
        lt = index._local_var_types(fi)
        for node in index._own_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            r = index.resolve_call(fi, node, la, lt)
            if r in mutators:
                mname = r.split(":")[1]
                findings.append(Finding(
                    "FSM104", fi.module, fi.qual, mname, fi.path,
                    node.lineno,
                    f"StateStore mutator `{mname}` is called outside "
                    "the raft apply path; the write never enters the "
                    "log and exists on this server only",
                    hint="propose a raft entry and let the FSM apply "
                         "it, or baseline with a justification if "
                         "this component is deliberately raft-free"))
    return findings


_MUTATING_METHODS = {"pop", "clear", "setdefault", "update", "append",
                     "add", "discard", "insert", "remove", "extend"}


def _store_mutators(index: PackageIndex, store_ck: str) -> Set[str]:
    """StateStore methods that write replicated state: any method that
    subscript-stores into self._t, deletes from it, or calls a
    write-barrier helper (self._bump*)."""
    out: Set[str] = set()
    ci = index.classes.get(store_ck)
    if ci is None:
        return out
    for mname, fkey in ci.methods.items():
        fi = index.functions[fkey]
        writes = False
        for node in index._own_nodes(fi):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target] if isinstance(
                               node, ast.AugAssign) else node.targets)
                for t in targets:
                    if _writes_self_table(t):
                        writes = True
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and d.startswith("self._bump"):
                    writes = True
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _MUTATING_METHODS:
                    base = node.func.value
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    bd = _dotted(base)
                    if bd == "self._t":
                        writes = True
        if writes:
            out.add(fkey)
    # transitive closure within the class: a method calling a mutator
    # is a mutator
    changed = True
    while changed:
        changed = False
        for mname, fkey in ci.methods.items():
            if fkey in out:
                continue
            if index.callees(fkey) & out:
                out.add(fkey)
                changed = True
    return out


def _writes_self_table(target) -> bool:
    """Matches self._t[...] = / self._t[...][k] = style stores."""
    node = target
    while isinstance(node, ast.Subscript):
        node = node.value
    d = _dotted(node)
    return bool(d and (d == "self._t" or d.startswith("self._t.")))
