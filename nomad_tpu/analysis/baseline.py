"""Baseline (suppression) file for nomadlint.

Format: a TOML subset (parsed here by hand — the container's Python
predates tomllib and the repo adds no deps):

    version = 1

    [[suppress]]
    rule = "FSM104"
    key = "FSM104:nomad_tpu.scheduler.harness:Harness.submit_plan:*"
    justification = "why this is accepted, mandatory"

`key` matches Finding.key (rule:module:func:symbol) and may use
fnmatch-style wildcards so one entry can cover a family of symbols.
Every entry MUST carry a non-empty justification; loading fails loudly
otherwise — an unexplained suppression is indistinguishable from a
swept-under-the-rug bug.
"""
from __future__ import annotations

import fnmatch
from typing import Dict, List


class BaselineError(Exception):
    pass


_HEADER = """\
# nomadlint baseline: accepted pre-existing findings.
#
# Keys match Finding.key = "RULE:module:qualname:symbol" (fnmatch
# wildcards allowed). Every entry MUST explain why the finding is
# accepted — the analyzer refuses to load entries without a
# justification. Remove entries as the underlying code is fixed; stale
# entries are reported as warnings (`--prune-stale` rewrites the file
# without them).

version = 1
"""


class Baseline:
    def __init__(self, entries: List[Dict[str, str]]):
        self.entries = entries

    def keys(self) -> List[str]:
        return [e["key"] for e in self.entries]

    def matches(self, finding_key: str) -> bool:
        return self.match_key(finding_key) is not None

    def match_key(self, finding_key: str):
        for e in self.entries:
            if fnmatch.fnmatchcase(finding_key, e["key"]):
                return e["key"]
        return None

    def without(self, dead_keys) -> "Baseline":
        dead = set(dead_keys)
        return Baseline([e for e in self.entries
                         if e["key"] not in dead])

    def render(self) -> str:
        """Regenerate the TOML-subset text (used by --prune-stale)."""
        parts = [_HEADER]
        for e in self.entries:
            parts.append("\n[[suppress]]")
            for k in ("rule", "key", "justification"):
                if k in e:
                    parts.append(f'{k} = "{e[k]}"')
            for k in sorted(e):
                if k not in ("rule", "key", "justification"):
                    parts.append(f'{k} = "{e[k]}"')
        return "\n".join(parts) + "\n"

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.render())


def _parse_scalar(raw: str, path: str, lineno: int):
    raw = raw.strip()
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw.startswith("'") and raw.endswith("'") and len(raw) >= 2:
        return raw[1:-1]
    try:
        return int(raw)
    except ValueError:
        raise BaselineError(
            f"{path}:{lineno}: unquoted non-integer value {raw!r}")


def _strip_comment(line: str) -> str:
    out = []
    quote = None
    for ch in line:
        if quote:
            out.append(ch)
            if ch == quote:
                quote = None
        elif ch in ('"', "'"):
            quote = ch
            out.append(ch)
        elif ch == "#":
            break
        else:
            out.append(ch)
    return "".join(out)


def parse_baseline_text(text: str, path: str = "<baseline>") -> Baseline:
    entries: List[Dict[str, str]] = []
    current: Dict[str, str] = {}
    in_suppress = False
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = _strip_comment(line).strip()
        if not stripped:
            continue
        if stripped == "[[suppress]]":
            if in_suppress:
                entries.append(current)
            current = {}
            in_suppress = True
            continue
        if stripped.startswith("["):
            raise BaselineError(
                f"{path}:{lineno}: unsupported table {stripped!r}")
        if "=" not in stripped:
            raise BaselineError(
                f"{path}:{lineno}: expected key = value")
        k, _, v = stripped.partition("=")
        k = k.strip()
        val = _parse_scalar(v, path, lineno)
        if in_suppress:
            current[k] = val
        # top-level keys (version = 1) are accepted and ignored
    if in_suppress:
        entries.append(current)

    for e in entries:
        if "key" not in e:
            raise BaselineError(f"{path}: [[suppress]] entry missing "
                                f"'key' ({e})")
        if "rule" not in e:
            raise BaselineError(f"{path}: entry {e['key']!r} missing "
                                "'rule'")
        just = str(e.get("justification", "")).strip()
        if not just:
            raise BaselineError(
                f"{path}: entry {e['key']!r} has no justification — "
                "every suppression must explain why the finding is "
                "accepted")
        if not str(e["key"]).startswith(str(e["rule"])):
            raise BaselineError(
                f"{path}: entry key {e['key']!r} does not start with "
                f"its rule {e['rule']!r}")
    return Baseline(entries)


def load_baseline(path: str) -> Baseline:
    with open(path, "r", encoding="utf-8") as f:
        return parse_baseline_text(f.read(), path)
