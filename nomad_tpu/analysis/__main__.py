"""CLI for nomadlint.

Exit-code contract (stable, scripted against by CI):

  0  no unsuppressed findings (clean, or everything baselined)
  1  at least one unsuppressed ERROR-tier finding
  2  baseline/config error (unjustified entry, unreadable file)
  3  unsuppressed WARN-tier findings only (advisory heuristics:
     LOCK302 / SHARD403 / ALIAS503 / OBS802 / RACE903)

`--no-baseline` is a REPORTING mode, not a gating mode: it lists every
finding (each tagged with whether the checked-in baseline would
suppress it) but the exit code is still computed from the
baseline-aware verdict — so `--no-baseline --json` in a CI pipeline
does not fail a clean tree just because accepted findings exist.

`--paths FILE...` is file-scoped INCREMENTAL mode for pre-commit
hooks: the whole package is still indexed (cross-file facts — mesh
reachability, spec reference fingerprints — need the full call
graph), but only findings in the named files are reported, and the
registry-rot/coverage rules (SCORE603/SCORE604) are muted because a
per-file view cannot judge them.  CI must keep running WITHOUT
`--paths` so the whole-package invariants stay enforced.

`--diff` resolves the changed-file set from `git diff --name-only
HEAD` and feeds it to the same --paths machinery (the pre-commit
ergonomic).  It refuses cleanly (exit 2) outside a git checkout.

`--cache-dir DIR` turns on the on-disk incremental index cache:
parsed ASTs are stored per file keyed by content hash, so repeat runs
only re-parse what changed.  Off by default — CI runs cold on purpose
so a poisoned cache can never mask a finding.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from . import (ANALYZER_VERSION, BaselineError, analyze,
               default_baseline_path, load_baseline, pass_of)


def _exit_code(rep) -> int:
    if rep.errors:
        return 1
    if rep.warnings:
        return 3
    return 0


def _diff_paths() -> list:
    """Changed .py files from git (worktree vs HEAD, plus staged and
    untracked), for --diff mode.  Raises RuntimeError outside a git
    checkout or without git."""
    here = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=here, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=here, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"git unavailable: {e}")
    if out.returncode != 0:
        raise RuntimeError(
            (out.stderr or "git diff failed").strip())
    names = out.stdout.splitlines()
    if untracked.returncode == 0:
        names += untracked.stdout.splitlines()
    return sorted({os.path.join(here, n) for n in names
                   if n.endswith(".py") and os.path.exists(
                       os.path.join(here, n))})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="nomadlint: FSM determinism / jit purity / lock "
                    "discipline / SPMD partition safety / buffer "
                    "aliasing / scoring drift analyzer",
        epilog="exit codes: 0 clean, 1 unsuppressed errors, "
               "2 baseline error, 3 unsuppressed warnings only")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding (tagged with its "
                         "baseline status); the EXIT CODE still "
                         "follows the baseline-aware verdict")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=None,
                    help="alternate baseline file "
                         f"(default: {default_baseline_path()})")
    ap.add_argument("--prune-stale", action="store_true",
                    help="rewrite the baseline file without entries "
                         "that no longer match any finding")
    ap.add_argument("--paths", nargs="+", metavar="FILE", default=None,
                    help="file-scoped incremental mode: report ONLY "
                         "findings in these files (pre-commit); "
                         "SCORE603/SCORE604 are muted — CI must run "
                         "without --paths")
    ap.add_argument("--diff", action="store_true",
                    help="pre-commit mode: resolve changed files from "
                         "`git diff --name-only HEAD` (plus untracked) "
                         "and run as if passed via --paths; refuses "
                         "outside a git checkout")
    ap.add_argument("--cache-dir", default=None, metavar="DIR",
                    help="on-disk incremental index cache (per-file "
                         "content-hash keyed ASTs); off by default so "
                         "CI always runs cold")
    args = ap.parse_args(argv)
    if args.diff and args.paths:
        print("--diff and --paths are mutually exclusive (--diff IS "
              "a computed --paths)", file=sys.stderr)
        return 2
    if args.diff:
        try:
            diff_paths = _diff_paths()
        except RuntimeError as e:
            print(f"--diff needs a git checkout: {e}", file=sys.stderr)
            return 2
        if not diff_paths:
            print(f"nomadlint v{ANALYZER_VERSION}: --diff found no "
                  "changed .py files")
            return 0
        args.paths = diff_paths
    if args.paths and args.prune_stale:
        # a partial index makes most baseline entries look stale;
        # pruning on that view would wrongly delete live entries
        print("--prune-stale needs the whole-package view; run it "
              "without --paths", file=sys.stderr)
        return 2

    bl_path = args.baseline or default_baseline_path()
    try:
        baseline = load_baseline(bl_path)
        rep = analyze(baseline=baseline, paths=args.paths,
                      cache_dir=args.cache_dir)
    except BaselineError as e:
        print(f"baseline error: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        if args.baseline is not None:
            print(f"baseline error: {e}", file=sys.stderr)
            return 2
        baseline = None
        rep = analyze(use_baseline=False, paths=args.paths,
                      cache_dir=args.cache_dir)

    if args.prune_stale and rep.stale_baseline_keys:
        pruned = baseline.without(rep.stale_baseline_keys)
        pruned.save(bl_path)
        print(f"pruned {len(rep.stale_baseline_keys)} stale baseline "
              f"entr{'y' if len(rep.stale_baseline_keys) == 1 else 'ies'}"
              f" from {bl_path}", file=sys.stderr)
        rep = analyze(baseline=pruned)

    shown = (rep.findings + rep.suppressed) if args.no_baseline \
        else rep.findings
    shown = sorted(shown, key=lambda f: (f.path, f.line, f.rule))
    suppressed_keys = {id(f) for f in rep.suppressed}

    if args.json:
        print(json.dumps({
            "version": rep.version,
            "unsuppressed": [
                vars(f) | {"key": f.key, "severity": f.severity,
                           "pass": pass_of(f.rule),
                           "baselined": id(f) in suppressed_keys}
                for f in shown],
            "suppressed": len(rep.suppressed),
            "stale_baseline_keys": rep.stale_baseline_keys,
            "stale_suggestions": rep.stale_suggestions,
            "by_rule": rep.counts_by_rule(),
            "by_pass": rep.counts_by_pass(),
            "errors": len(rep.errors),
            "warnings": len(rep.warnings),
            "exit_code": _exit_code(rep),
        }, indent=1))
    else:
        for f in shown:
            tag = " [baselined]" if id(f) in suppressed_keys else ""
            sev = "" if f.severity == "error" else " (warn)"
            print(f.render() + tag + sev)
        # a partial --paths view strands most baseline entries; only a
        # whole-package run can call an entry stale
        for k in ([] if args.paths else rep.stale_baseline_keys):
            near = rep.stale_suggestions.get(k)
            extra = f" (nearest current key: {near})" if near else ""
            print("warning: stale baseline entry matches nothing: "
                  f"{k}{extra}", file=sys.stderr)
        print(f"nomadlint v{rep.version}: "
              f"{len(rep.errors)} error(s), "
              f"{len(rep.warnings)} warning(s), "
              f"{len(rep.suppressed)} baselined"
              + (f" [{rep.counts_by_rule()}]" if rep.findings else ""))
    return _exit_code(rep)


if __name__ == "__main__":
    sys.exit(main())
