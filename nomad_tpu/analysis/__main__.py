"""CLI: `python -m nomad_tpu.analysis` — exit 0 iff zero unsuppressed
findings (baseline errors exit 2)."""
from __future__ import annotations

import argparse
import json
import sys

from . import (ANALYZER_VERSION, BaselineError, analyze,
               default_baseline_path, load_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="nomadlint: FSM determinism / jit purity / lock "
                    "discipline analyzer")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring baseline.toml")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=None,
                    help="alternate baseline file "
                         f"(default: {default_baseline_path()})")
    args = ap.parse_args(argv)

    try:
        baseline = None
        if not args.no_baseline:
            path = args.baseline or default_baseline_path()
            baseline = load_baseline(path)
        rep = analyze(baseline=baseline, use_baseline=not args.no_baseline)
    except BaselineError as e:
        print(f"baseline error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "version": rep.version,
            "unsuppressed": [vars(f) | {"key": f.key}
                             for f in rep.findings],
            "suppressed": len(rep.suppressed),
            "stale_baseline_keys": rep.stale_baseline_keys,
            "by_rule": rep.counts_by_rule(),
        }, indent=1))
    else:
        for f in rep.findings:
            print(f.render())
        for k in rep.stale_baseline_keys:
            print(f"warning: stale baseline entry matches nothing: {k}",
                  file=sys.stderr)
        print(f"nomadlint v{rep.version}: "
              f"{len(rep.findings)} finding(s), "
              f"{len(rep.suppressed)} baselined"
              + (f" [{rep.counts_by_rule()}]" if rep.findings else ""))
    return 0 if rep.ok else 1


if __name__ == "__main__":
    sys.exit(main())
