"""Pass 6: scoring-spec conformance (SCORE6xx, v3).

The exact scorer used to be replicated float-order-exact in five hand
backends, held identical only by backend-vs-backend drift fingerprints
(v2 of this pass).  `nomad_tpu/solver/score_spec.py` is now the single
declarative source of truth: each term carries its exact float-op
sequence, constants and combine order, and the backends split in two:

  * DRIVEN — the host twin (`host.host_solve_kernel.group_scores`)
    and the jit wave scorer (`kernel.solve_kernel.group_scores`) call
    `score_spec.evaluate_wave`; they are bit-identical to the spec by
    construction and must contain NO scoring arithmetic of their own.
  * HAND, SPEC-VERIFIED — the shortlist VMEM twin
    (`kernel._sl_eval`), the pallas fused pass (`_wave_tile_kernel`)
    and the native C++ engine (`host_solve.cc`) stay hand-written for
    performance; this pass compiles the spec into per-term reference
    fingerprints and statically proves each of them implements the
    spec.

The spec registry (`score_spec.TERMS`) is a pure literal read with
`ast.literal_eval` — the analyzer never imports the solver.  Each
entry names the reference term function, the fingerprint groups
(group -> the assignment-target aliases backends may use), whether a
group compares as a constant SET only (loop structure genuinely
differs per backend), and exactly which backends must implement it.

A term fingerprint is the multiset of float CONSTANTS plus the counts
of arithmetic ops (+ - * / ** neg) in the group's assignments — leaf
variable names, indexing and where/select CONDITIONS are excluded
(they legitimately differ between vectorized numpy, pallas refs and
scalar C++), cast wrappers (`f32(...)`, `.astype(...)`) are
transparent.  The native backend is tokenized from C++ source with a
small translation layer (`std::pow` -> `**`,
`std::min(std::max(x,a),b)` -> `clip`, ternaries drop their condition
like `where`, bool-to-float coercions fold away, subscripts are
stripped).

Rules
  SCORE601  a backend's term fingerprint diverges from the SPEC
            reference (or a spec-driven backend carries hand scoring
            arithmetic — by construction that IS drift-vs-spec)
  SCORE602  scoring-shaped arithmetic outside the spec and the
            registered sites: an assignment combining two or more
            registered score terms (the "new term hand-added in one
            backend" shape) — move it into the spec / a registered
            site
  SCORE603  a registered site no longer resolves, or the spec registry
            itself is missing/unparseable (registry rot: the
            conformance check would go silently blind) (error tier;
            baseline with a justification for intentional removals)
  SCORE604  spec/backend coverage drift: a backend misses a spec term
            it is registered for, implements a term it is NOT
            registered for, a term names an unknown backend, or a
            driven backend no longer calls the spec term loop

Configs without a spec-kind site row (fixtures, older registries)
fall back to the v2 behavior: the first registered site is the drift
reference and terms are grouped by the built-in TERM_NAMES map.
"""
from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisConfig, Finding, FuncInfo, PackageIndex, \
    _dotted

# ---------------------------------------------------------- registry
@dataclasses.dataclass(frozen=True)
class ScorerSite:
    backend: str          # "spec" | "host" | "kernel" | ...
    kind: str             # "spec" | "driven" | "python" | "native"
    site: str             # spec: the spec MODULE name; python/driven:
                          # "module:qualname" fnmatch pattern; native:
                          # a package-relative source path
    terms: Tuple[str, ...] = ()   # v2 path only: terms this backend
                                  # must carry; empty = DEFAULT_TERMS


#: the spec module every v3 registry row is verified against
SPEC_MODULE = "nomad_tpu.solver.score_spec"
#: the term-loop entry point every DRIVEN backend must call
DRIVEN_ENTRY = "evaluate_wave"

DEFAULT_TERMS = ("free", "binpack", "anti", "pen", "n_scorers",
                 "total", "spread")

#: the scoring-site registry: the spec row is the reference; "driven"
#: rows must defer to it, "python"/"native" rows are hand replicas
#: verified against it.  Adding a backend scorer = adding a row here
#: AND listing the backend in the relevant score_spec.TERMS entries;
#: writing scoring arithmetic anywhere else trips SCORE602.
DEFAULT_SCORER_SITES: Tuple[ScorerSite, ...] = (
    ScorerSite("spec", "spec", SPEC_MODULE),
    ScorerSite("host", "driven",
               "nomad_tpu.solver.host:host_solve_kernel.group_scores"),
    ScorerSite("kernel", "driven",
               "nomad_tpu.solver.kernel:solve_kernel.group_scores"),
    ScorerSite("shortlist", "python",
               "nomad_tpu.solver.kernel:solve_kernel._sl_eval"),
    ScorerSite("pallas", "python",
               "nomad_tpu.solver.pallas_kernel:_wave_tile_kernel"),
    ScorerSite("native", "native",
               os.path.join("nomad_tpu", "solver", "native",
                            "host_solve.cc")),
)

# v2 fallback: canonical term -> assignment-target names (the v3 path
# derives this mapping from score_spec.TERMS instead)
TERM_NAMES: Dict[str, Tuple[str, ...]] = {
    "free": ("free_cpu", "free_mem"),
    "binpack": ("raw", "binpack"),
    "anti": ("anti",),
    "pen": ("pen", "pen_score", "pen_sc"),
    "n_scorers": ("n_scorers",),
    "total": ("total",),
    "spread": ("cur", "boost", "targeted", "delta_boost", "even",
               "contrib", "spread_total", "sp_total", "minc", "maxc",
               "desired"),
}
# terms compared as {const set} only (loop structure differs/backend)
CONST_SET_TERMS = {"spread"}

# where/select-family calls whose FIRST argument is a condition
_COND_CALLS = {"where", "select"}
# calls that are transparent casts
_CAST_CALLS = {"f32", "float32", "int32", "astype", "asarray", "int8",
               "int16", "uint32", "u32", "i32", "float", "f64",
               "float64", "bool_"}
# composite term names whose co-occurrence outside a registered site
# is scoring-shaped arithmetic (SCORE602)
_COMPOSITE_NAMES = {"binpack", "anti", "pen", "pen_score", "pen_sc",
                    "aff_score", "aff_sc", "spread_total", "sp_total",
                    "n_scorers"}


@dataclasses.dataclass
class TermPrint:
    consts: Tuple[float, ...] = ()       # sorted multiset
    ops: Tuple[Tuple[str, int], ...] = ()  # sorted (op, count)
    const_set: Tuple[float, ...] = ()    # sorted set (spread policy)

    def describe(self) -> str:
        ops = ", ".join(f"{o}x{n}" for o, n in self.ops) or "-"
        return f"ops[{ops}] consts{list(self.consts)}"

    def empty(self) -> bool:
        return not self.consts and not self.ops


# ====================================================== python extract
class _PyPrinter:
    """Collect one term-group fingerprint from python assignment
    expressions."""

    def __init__(self):
        self.consts: List[float] = []
        self.ops: Dict[str, int] = {}

    def feed(self, node) -> None:
        self._walk(node)

    def _op(self, name: str) -> None:
        self.ops[name] = self.ops.get(name, 0) + 1

    def _walk(self, node) -> None:
        if node is None:
            return
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, float)) \
                    and not isinstance(node.value, bool):
                self.consts.append(float(node.value))
            return
        if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                        ast.USub):
            # fold -1.0 into a constant; keep neg as an op otherwise
            if isinstance(node.operand, ast.Constant) and isinstance(
                    node.operand.value, (int, float)):
                self.consts.append(-float(node.operand.value))
                return
            self._op("neg")
            self._walk(node.operand)
            return
        if isinstance(node, ast.BinOp):
            opname = {ast.Add: "add", ast.Sub: "sub", ast.Mult: "mul",
                      ast.Div: "div", ast.Pow: "pow"}.get(
                          type(node.op))
            if opname:
                self._op(opname)
            self._walk(node.left)
            self._walk(node.right)
            return
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            last = name.rsplit(".", 1)[-1]
            if not last and isinstance(node.func, ast.Attribute):
                # method on a non-trivial expression, e.g.
                # `(a + b).astype(f32)` — _dotted can't chain it
                last = node.func.attr
            if last in _CAST_CALLS:
                # transparent: f32(20.0) -> 20.0, x.astype(f32) -> x
                if isinstance(node.func, ast.Attribute) \
                        and last == "astype":
                    self._walk(node.func.value)
                    return
                for a in node.args:
                    self._walk(a)
                return
            args = node.args
            if last in _COND_CALLS and args:
                args = args[1:]          # drop the condition
            for a in args:
                self._walk(a)
            for kw in node.keywords:
                if kw.arg not in ("axis", "keepdims", "dtype",
                                  "num_keys", "mode"):
                    self._walk(kw.value)
            return
        if isinstance(node, ast.Subscript):
            # indexing is layout plumbing, not scoring structure
            self._walk(node.value)
            return
        if isinstance(node, (ast.Name, ast.Attribute, ast.Compare,
                             ast.BoolOp)):
            # leaves and conditions are excluded by design
            return
        if isinstance(node, ast.IfExp):
            self._walk(node.body)
            self._walk(node.orelse)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child)


def _collect_assigns(index: PackageIndex, fi: FuncInfo,
                     names: Tuple[str, ...], nested: bool
                     ) -> List[ast.AST]:
    out: List[ast.AST] = []
    keys = [fi.key]
    while keys:
        cur = index.functions[keys.pop(0)]
        for node in index._own_nodes(cur):
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name):
                tgt = node.target.id
            if tgt in names:
                out.append(node)
        if nested:
            keys.extend(cur.nested)
    return out


def _term_assignments(index: PackageIndex, fi: FuncInfo,
                      names: Tuple[str, ...]) -> List[ast.AST]:
    """Assignments to any of `names` in the site function INCLUDING its
    nested helper defs (kernel's spread lives in a nested
    `one_spread`); when a term is not defined there at all, climb the
    enclosing-def chain own-nodes-only (host's `pen_score` lives in
    host_solve_kernel's scope, one level above group_scores — own
    nodes only, so a sibling nested scorer is not double-collected)."""
    out = _collect_assigns(index, fi, names, nested=True)
    cur: Optional[FuncInfo] = fi
    while not out and cur is not None and cur.parent:
        cur = index.functions.get(cur.parent)
        if cur is None:
            break
        out = _collect_assigns(index, cur, names, nested=False)
    return out


def _print_nodes(nodes: Sequence[ast.AST]) -> TermPrint:
    p = _PyPrinter()
    for node in nodes:
        p.feed(node.value)
        if isinstance(node, ast.AugAssign):
            p._op({ast.Add: "add", ast.Sub: "sub",
                   ast.Mult: "mul", ast.Div: "div"}.get(
                       type(node.op), "add"))
    return TermPrint(consts=tuple(sorted(p.consts)),
                     ops=tuple(sorted(p.ops.items())),
                     const_set=tuple(sorted(set(p.consts))))


def python_fingerprint(index: PackageIndex, fi: FuncInfo,
                       terms: Sequence[str],
                       names: Optional[Dict[str, Tuple[str, ...]]]
                       = None) -> Dict[str, TermPrint]:
    names = names or TERM_NAMES
    prints: Dict[str, TermPrint] = {}
    for term in terms:
        nodes = _term_assignments(index, fi, tuple(names[term]))
        if not nodes:
            continue
        prints[term] = _print_nodes(nodes)
    return prints


# ====================================================== spec registry
def load_spec_literal(index: PackageIndex, module: str, name: str):
    """Evaluate a module-level pure-literal assignment (TERMS /
    SPEC_VERSION) from the spec module's AST — never imports it."""
    mi = index.modules.get(module)
    if mi is None:
        return None
    for node in mi.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name:
            try:
                return ast.literal_eval(node.value)
            except (ValueError, SyntaxError, TypeError):
                return None
    return None


def spec_reference(index: PackageIndex, module: str = SPEC_MODULE):
    """Compile the spec into its reference fingerprints.

    Returns (terms_reg, prints, names_map, const_set_groups, errors):
    `terms_reg` the parsed TERMS literal, `prints` group -> TermPrint
    fingerprinted from the registered term functions, `names_map`
    group -> backend assignment-target aliases, `errors` human
    strings for anything that failed to resolve (registry rot)."""
    errors: List[str] = []
    terms_reg = load_spec_literal(index, module, "TERMS")
    if not terms_reg:
        return None, {}, {}, set(), [
            f"spec registry `{module}.TERMS` missing or not a pure "
            "literal"]
    prints: Dict[str, TermPrint] = {}
    names_map: Dict[str, Tuple[str, ...]] = {}
    const_set_groups: Set[str] = set()
    for entry in terms_reg:
        fkey = f"{module}:{entry['fn']}"
        fi = index.functions.get(fkey)
        if fi is None and entry.get("groups"):
            errors.append(
                f"spec term `{entry['name']}` names function "
                f"`{entry['fn']}` which does not exist in {module}")
            continue
        for group, aliases in (entry.get("groups") or {}).items():
            names_map[group] = tuple(aliases)
            if entry.get("const_set"):
                const_set_groups.add(group)
            nodes = _collect_assigns(index, fi, tuple(aliases),
                                     nested=True)
            prints[group] = _print_nodes(nodes)
    return terms_reg, prints, names_map, const_set_groups, errors


# ====================================================== native extract
_C_FLOAT = re.compile(r"(?<![\w.])(-?\d+(?:\.\d*)?(?:e-?\d+)?)f?\b")
_C_STMT = re.compile(
    r"(?:const\s+)?(?:float|double|auto)?\s*"
    r"(?P<name>\w+)\s*(?P<aug>[+\-*/]?)=\s*(?P<rhs>[^;]+);")


def _c_statements(src: str) -> List[Tuple[str, str, str]]:
    """(name, augop, rhs) for every simple assignment statement, with
    line comments stripped and continuation lines joined."""
    src = re.sub(r"//[^\n]*", "", src)
    src = re.sub(r"\s+", " ", src)
    return [(m.group("name"), m.group("aug"), m.group("rhs"))
            for m in _C_STMT.finditer(src)]


def _c_normalize(rhs: str) -> str:
    """Translate C++ scoring idioms onto the python canonical form."""
    # subscripts are plumbing: strip [...] including nested ones
    prev = None
    while prev != rhs:
        prev = rhs
        rhs = re.sub(r"\[[^\[\]]*\]", "", rhs)
    # bool->float coercions fold away like implicit casts
    rhs = re.sub(r"\(\s*\w+\s*\?\s*1\.0f?\s*:\s*0\.0f?\s*\)", "B", rhs)
    # clip spelled as min(max(x, lo), hi)
    rhs = re.sub(
        r"std::min\s*\(\s*std::max\s*\(([^,]+),([^)]+)\)\s*,([^)]+)\)",
        r"clip(\1,\2,\3)", rhs)
    rhs = rhs.replace("std::pow", "POW").replace("std::floor", "floor")
    rhs = rhs.replace("std::max", "MAXF").replace("std::min", "MINF")
    return rhs


def _c_term_print(stmts: List[Tuple[str, str, str]],
                  names: Tuple[str, ...], term: str) -> TermPrint:
    consts: List[float] = []
    ops: Dict[str, int] = {}

    def add_op(name, n=1):
        ops[name] = ops.get(name, 0) + n

    for name, aug, rhs in stmts:
        if name not in names:
            continue
        rhs = _c_normalize(rhs)
        # ternary: drop the condition (like where)
        if "?" in rhs:
            cond, _, branches = rhs.partition("?")
            rhs = branches.replace(":", " ")
        if aug:
            add_op({"+": "add", "-": "sub", "*": "mul",
                    "/": "div"}[aug])
        # constants (before op counting so signs bind to numbers)
        for m in _C_FLOAT.finditer(rhs):
            consts.append(float(m.group(1)))
        body = _C_FLOAT.sub("C", rhs)
        add_op("pow", body.count("POW"))
        body = body.replace("POW", "")
        # unary minus: only when no operand precedes it (start of the
        # expression or right after an opener/separator); a minus
        # after an operand is the binary sub counted below
        for m in re.finditer(r"(?:^|[(,?:=])\s*-\s*(?=[A-Za-z_(])",
                             body.strip()):
            add_op("neg")
        # binary ops: a token on each side
        for opch, opname in (("+", "add"), ("*", "mul"),
                             ("/", "div")):
            add_op(opname, len(re.findall(
                re.escape(opch) if opch != "+" else r"(?<!\+)\+(?!\+)",
                body)))
        # binary minus: preceded by an identifier/paren/constant
        add_op("sub", len(re.findall(r"(?<=[\w)C])\s*-\s*(?=[\w(C])",
                                     body)))
    # neg got double-counted as sub when preceded by '(' -> already
    # excluded by the lookbehind; pow args contribute their own consts
    zero = {k: v for k, v in ops.items() if v}
    return TermPrint(consts=tuple(sorted(consts)),
                     ops=tuple(sorted(zero.items())),
                     const_set=tuple(sorted(set(consts))))


def native_fingerprint(path: str, terms: Sequence[str],
                       names: Optional[Dict[str, Tuple[str, ...]]]
                       = None) -> Dict[str, TermPrint]:
    names = names or TERM_NAMES
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    # scope to the scoring region when the source carries the standard
    # section markers, so same-named scratch vars elsewhere (top-k
    # scratch `score`, commit loops) don't pollute the fingerprint
    lo = src.find("batched scoring")
    hi = src.find("per-group top-k")
    if 0 <= lo < hi:
        src = src[lo:hi]
    stmts = _c_statements(src)
    out: Dict[str, TermPrint] = {}
    for term in terms:
        tp = _c_term_print(stmts, tuple(names[term]), term)
        if tp.consts or tp.ops:
            out[term] = tp
    return out


# ============================================================== pass
def run_score_pass(index: PackageIndex, cfg: AnalysisConfig,
                   package_dir: Optional[str] = None
                   ) -> List[Finding]:
    sites = getattr(cfg, "scorer_sites", None) or DEFAULT_SCORER_SITES
    spec_sites = [s for s in sites if s.kind == "spec"]
    findings: List[Finding] = []
    site_fn_patterns: List[str] = []
    for site in sites:
        if site.kind == "spec":
            site_fn_patterns.append(site.site + ":*")
        elif site.kind in ("python", "driven"):
            site_fn_patterns.append(site.site)

    if spec_sites:
        findings += _spec_conformance(index, sites, spec_sites[0],
                                      package_dir)
    else:
        findings += _legacy_drift(index, sites, package_dir)

    # ---- SCORE602: scoring-shaped arithmetic outside the registry
    for fkey, fi in sorted(index.functions.items()):
        base = fkey.split("#")[0]
        if any(fnmatch.fnmatchcase(base, p) or
               fnmatch.fnmatchcase(_parent_chain(index, fi), p)
               for p in site_fn_patterns):
            continue
        if fi.module.startswith("nomad_tpu.analysis"):
            continue
        for node in index._own_nodes(fi):
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            val = node.value
            used: Set[str] = set()
            for sub in ast.walk(val):
                if isinstance(sub, ast.Name) \
                        and sub.id in _COMPOSITE_NAMES:
                    used.add(sub.id)
                elif isinstance(sub, ast.Attribute) \
                        and sub.attr in _COMPOSITE_NAMES:
                    used.add(sub.attr)
            if len(used) >= 2:
                findings.append(Finding(
                    "SCORE602", fi.module, fi.qual,
                    "+".join(sorted(used)), fi.path, node.lineno,
                    "scoring-shaped arithmetic (combines "
                    f"{sorted(used)}) outside the registered scorer "
                    "sites; a term added here exists in ONE backend "
                    "only and the twins silently diverge",
                    hint="move the logic into the scoring spec "
                         "(solver/score_spec.py) or register the site "
                         "in analysis/score_pass.py"))
    return findings


# ------------------------------------------------------ v3: spec path
def _spec_conformance(index: PackageIndex, sites: Sequence[ScorerSite],
                      spec_site: ScorerSite,
                      package_dir: Optional[str]) -> List[Finding]:
    findings: List[Finding] = []
    terms_reg, spec_prints, names_map, const_set_groups, errors = \
        spec_reference(index, spec_site.site)
    mi = index.modules.get(spec_site.site)
    spec_path = mi.path if mi is not None else spec_site.site
    for err in errors:
        findings.append(Finding(
            "SCORE603", "-", "-", spec_site.backend, spec_path, 0,
            err + "; the spec-conformance check is blind",
            hint="fix score_spec.TERMS (it must stay a pure literal "
                 "naming existing term functions)"))
    if not terms_reg:
        return findings

    known_backends = {s.backend for s in sites}
    all_groups = tuple(names_map)
    # group -> the term entry that owns it
    group_term: Dict[str, dict] = {}
    for entry in terms_reg:
        for group in (entry.get("groups") or {}):
            group_term[group] = entry
        for b in entry.get("backends", ()):
            if b not in known_backends:
                findings.append(Finding(
                    "SCORE604", "-", "spec", entry["name"], spec_path,
                    0,
                    f"spec term `{entry['name']}` names backend `{b}` "
                    "which has no row in the scoring-site registry; "
                    "its conformance is never checked",
                    hint="add the ScorerSite row in "
                         "analysis/score_pass.py or fix the term's "
                         "backends tuple"))

    for site in sites:
        if site.kind == "spec":
            continue
        if site.kind in ("python", "driven"):
            fkeys = index.match_funcs([site.site])
            if not fkeys:
                findings.append(_stale(site))
                continue
            fi = index.functions[fkeys[0]]
            path, line = fi.path, fi.node.lineno
            if site.kind == "driven":
                findings += _check_driven(index, site, fi, all_groups,
                                          names_map)
                continue
            fp = python_fingerprint(index, fi, all_groups, names_map)
        else:
            path = site.site if os.path.isabs(site.site) else \
                os.path.join(package_dir or "", site.site)
            if not os.path.exists(path):
                findings.append(_stale(site, native=True))
                continue
            fp, line = native_fingerprint(path, all_groups,
                                          names_map), 0
        # ---- coverage (SCORE604) + drift (SCORE601) per group
        for group in all_groups:
            entry = group_term[group]
            listed = site.backend in entry.get("backends", ())
            tp = fp.get(group)
            has = tp is not None and not tp.empty()
            if listed and not has:
                findings.append(Finding(
                    "SCORE604", "-", site.backend, group, path, line,
                    f"backend `{site.backend}` is registered for spec "
                    f"term `{entry['name']}` but carries no `{group}` "
                    "fingerprint (term missing in this backend)",
                    hint="replicate the term float-order-exactly from "
                         "score_spec, or drop the backend from the "
                         "term's backends tuple"))
                continue
            if not listed:
                if has:
                    findings.append(Finding(
                        "SCORE604", "-", site.backend, group, path,
                        line,
                        f"backend `{site.backend}` implements spec "
                        f"term `{entry['name']}` (group `{group}`) "
                        "but the term does not list it — coverage "
                        "drift: the fingerprint is never verified",
                        hint="add the backend to the term's backends "
                             "tuple in score_spec.TERMS"))
                continue
            a = spec_prints.get(group)
            if a is None:
                continue
            if group in const_set_groups:
                if set(a.const_set) != set(tp.const_set):
                    findings.append(_drift(site.backend, group, path,
                                           line, a, tp, "spec",
                                           consts_only=True))
            elif (a.consts, a.ops) != (tp.consts, tp.ops):
                findings.append(_drift(site.backend, group, path,
                                       line, a, tp, "spec"))
    return findings


def _check_driven(index: PackageIndex, site: ScorerSite, fi: FuncInfo,
                  all_groups: Tuple[str, ...],
                  names_map: Dict[str, Tuple[str, ...]]
                  ) -> List[Finding]:
    """A driven backend must (a) call the spec term loop and (b) carry
    ZERO scoring arithmetic of its own — any non-empty group
    fingerprint here is drift-vs-spec by construction."""
    findings: List[Finding] = []
    calls_spec = any(
        isinstance(n, ast.Call)
        and (_dotted(n.func) or "").rsplit(".", 1)[-1] == DRIVEN_ENTRY
        for n in ast.walk(fi.node))
    if not calls_spec:
        findings.append(Finding(
            "SCORE604", "-", site.backend, DRIVEN_ENTRY, fi.path,
            fi.node.lineno,
            f"spec-driven backend `{site.backend}` no longer calls "
            f"score_spec.{DRIVEN_ENTRY}; it is not evaluating the "
            "spec's terms at all",
            hint="drive the backend from score_spec.evaluate_wave "
                 "(or re-register it as a hand backend and replicate "
                 "every term)"))
    fp = python_fingerprint(index, fi, all_groups, names_map)
    for group, tp in sorted(fp.items()):
        if tp.empty():
            continue
        findings.append(Finding(
            "SCORE601", "-", site.backend, group, fi.path,
            fi.node.lineno,
            f"spec-driven backend `{site.backend}` carries hand "
            f"scoring arithmetic for `{group}` ({tp.describe()}); "
            "driven backends must defer every float op to score_spec "
            "(hand edits here silently drift from the spec)",
            hint="move the arithmetic into the term function in "
                 "solver/score_spec.py (both driven backends pick it "
                 "up) and delete it here"))
    return findings


def _stale(site: ScorerSite, native: bool = False) -> Finding:
    what = ("registered native scorer source"
            if native else "registered scorer site")
    return Finding(
        "SCORE603", "-", "-", site.backend, site.site, 0,
        f"{what} `{site.site}` (backend {site.backend}) resolves to "
        "nothing; the spec-conformance check is blind to this backend",
        hint="update the registry entry in analysis/score_pass.py (or "
             "AnalysisConfig.scorer_sites) after renaming the scorer; "
             "baseline with a justification for intentional removals")


# ------------------------------------------------- v2: legacy fallback
def _legacy_drift(index: PackageIndex, sites: Sequence[ScorerSite],
                  package_dir: Optional[str]) -> List[Finding]:
    findings: List[Finding] = []
    prints: List[Tuple[ScorerSite, str, Dict[str, TermPrint],
                       str, int]] = []
    for site in sites:
        terms = site.terms or DEFAULT_TERMS
        if site.kind == "python":
            fkeys = index.match_funcs([site.site])
            if not fkeys:
                findings.append(_stale(site))
                continue
            fi = index.functions[fkeys[0]]
            fp = python_fingerprint(index, fi, terms)
            prints.append((site, site.backend, fp, fi.path,
                           fi.node.lineno))
        else:
            path = site.site if os.path.isabs(site.site) else \
                os.path.join(package_dir or "", site.site)
            if not os.path.exists(path):
                findings.append(_stale(site, native=True))
                continue
            fp = native_fingerprint(path, terms)
            prints.append((site, site.backend, fp, site.site, 0))

    # ---- SCORE601: compare every backend against the reference
    if prints:
        ref_site, ref_name, ref_fp, ref_path, _ = prints[0]
        for site, backend, fp, path, line in prints[1:]:
            terms = site.terms or DEFAULT_TERMS
            for term in terms:
                a = ref_fp.get(term)
                b = fp.get(term)
                if a is None:
                    continue          # reference doesn't carry it
                if b is None:
                    findings.append(Finding(
                        "SCORE601", "-", backend, term, path, line,
                        f"backend `{backend}` is missing scoring term "
                        f"`{term}` (reference backend `{ref_name}` "
                        "carries it)",
                        hint="replicate the term float-order-exactly "
                             "or register the backend with an "
                             "explicit reduced term list"))
                    continue
                if term in CONST_SET_TERMS:
                    if set(a.const_set) != set(b.const_set):
                        findings.append(_drift(backend, term, path,
                                               line, a, b, ref_name,
                                               consts_only=True))
                elif (a.consts, a.ops) != (b.consts, b.ops):
                    findings.append(_drift(backend, term, path, line,
                                           a, b, ref_name))
    return findings


def _parent_chain(index: PackageIndex, fi: FuncInfo) -> str:
    """module:qual of the OUTERMOST enclosing def, so nested helpers of
    a registered site count as inside it."""
    cur = fi
    while cur.parent and cur.parent in index.functions:
        cur = index.functions[cur.parent]
    return cur.key.split("#")[0]


def _drift(backend: str, term: str, path: str, line: int,
           a: TermPrint, b: TermPrint, ref: str,
           consts_only: bool = False) -> Finding:
    what = ("constant set" if consts_only
            else "float-op fingerprint")
    return Finding(
        "SCORE601", "-", backend, term, path, line,
        f"scoring term `{term}` {what} diverges between backend "
        f"`{backend}` ({b.describe()}) and reference `{ref}` "
        f"({a.describe()}); the twins are no longer float-order-"
        "identical and placements can differ per backend",
        hint="make the term's constants and op structure identical in "
             "every registered backend (see STATIC_ANALYSIS.md "
             "SCORE6xx for the canonicalization rules)")
