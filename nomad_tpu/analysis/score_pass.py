"""Pass 6: cross-backend scoring drift (SCORE6xx).

The exact scorer is replicated float-order-exact in FOUR backends —
the numpy host twin (`host.group_scores`), the jit kernel twin
(`kernel.group_scores`), the shortlist VMEM twin
(`kernel._sl_eval`), the pallas fused pass (`_wave_tile_kernel`) —
plus the native C++ engine (`host_solve.cc`). Every new scoring term
must land in all of them with the same constants and the same float-op
structure, or placements silently diverge between backends (ROADMAP
item 5 names this replication the main drag on the learned-scorer and
in-kernel-preemption work).

This pass normalizes each REGISTERED scorer site into a canonical
per-term float-op fingerprint and fails on structural divergence:

  * terms are groups of assignments to canonical names (`free_cpu`/
    `free_mem`, `raw`+`binpack`, `anti`, `pen*`, `n_scorers`,
    `total`);
  * a term fingerprint is the multiset of float CONSTANTS plus the
    counts of arithmetic ops (+ - * / ** neg) in those assignments —
    leaf variable names, indexing and where/select CONDITIONS are
    excluded (they legitimately differ between vectorized numpy,
    pallas refs and scalar C++), cast wrappers (`f32(...)`,
    `.astype(...)`) are transparent;
  * the native backend is tokenized from C++ source with a small
    translation layer: `std::pow` -> `**`, `std::min(std::max(x,a),b)`
    -> `clip(a, b)`, ternaries drop their condition like `where`,
    bool-to-float `(c ? 1.0f : 0.0f)` folds away like an implicit
    cast, subscripts are stripped;
  * the `spread` term is compared as a SET of core constants only —
    its loop structure genuinely differs per backend (numpy
    take_along_axis vs pallas select-sum vs scalar C++).

Rules
  SCORE601  a registered backend's term fingerprint diverges from the
            reference backend (first site in the registry)
  SCORE602  scoring-shaped arithmetic outside the registered sites: an
            assignment combining two or more registered score terms
            (the "new term hand-added in one backend, or a fifth
            ad-hoc scorer" shape) — register the site or move the
            logic into a registered scorer
  SCORE603  a registered site no longer resolves (registry rot after a
            rename/refactor: the drift check would go silently blind)
            (warn tier)
"""
from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisConfig, Finding, FuncInfo, PackageIndex, \
    _dotted

# ---------------------------------------------------------- registry
@dataclasses.dataclass(frozen=True)
class ScorerSite:
    backend: str          # "host" | "kernel" | "shortlist" | ...
    kind: str             # "python" | "native"
    site: str             # "module:qualname" fnmatch pattern, or a
                          # package-relative source path for native
    terms: Tuple[str, ...] = ()   # terms this backend must carry;
                                  # empty = DEFAULT_TERMS


DEFAULT_TERMS = ("free", "binpack", "anti", "pen", "n_scorers",
                 "total", "spread")

#: the scoring-site registry: ONE row per backend replica of the exact
#: scorer. Adding a new backend scorer = adding a row here (and
#: keeping its float ops term-identical); writing scoring arithmetic
#: anywhere else trips SCORE602. The first row is the drift reference.
DEFAULT_SCORER_SITES: Tuple[ScorerSite, ...] = (
    ScorerSite("host", "python",
               "nomad_tpu.solver.host:host_solve_kernel.group_scores"),
    ScorerSite("kernel", "python",
               "nomad_tpu.solver.kernel:solve_kernel.group_scores"),
    ScorerSite("shortlist", "python",
               "nomad_tpu.solver.kernel:solve_kernel._sl_eval"),
    ScorerSite("pallas", "python",
               "nomad_tpu.solver.pallas_kernel:_wave_tile_kernel"),
    ScorerSite("native", "native",
               os.path.join("nomad_tpu", "solver", "native",
                            "host_solve.cc")),
)

# canonical term -> the assignment-target names that belong to it
TERM_NAMES: Dict[str, Tuple[str, ...]] = {
    "free": ("free_cpu", "free_mem"),
    "binpack": ("raw", "binpack"),
    "anti": ("anti",),
    "pen": ("pen", "pen_score", "pen_sc"),
    "n_scorers": ("n_scorers",),
    "total": ("total",),
    "spread": ("cur", "boost", "targeted", "delta_boost", "even",
               "contrib", "spread_total", "sp_total", "minc", "maxc",
               "desired"),
}
# terms compared as {const set} only (loop structure differs/backend)
CONST_SET_TERMS = {"spread"}

# where/select-family calls whose FIRST argument is a condition
_COND_CALLS = {"where", "select"}
# calls that are transparent casts
_CAST_CALLS = {"f32", "float32", "int32", "astype", "asarray", "int8",
               "int16", "uint32", "u32", "i32", "float", "f64",
               "float64", "bool_"}
# composite term names whose co-occurrence outside a registered site
# is scoring-shaped arithmetic (SCORE602)
_COMPOSITE_NAMES = {"binpack", "anti", "pen", "pen_score", "pen_sc",
                    "aff_score", "aff_sc", "spread_total", "sp_total",
                    "n_scorers"}


@dataclasses.dataclass
class TermPrint:
    consts: Tuple[float, ...] = ()       # sorted multiset
    ops: Tuple[Tuple[str, int], ...] = ()  # sorted (op, count)
    const_set: Tuple[float, ...] = ()    # sorted set (spread policy)

    def describe(self) -> str:
        ops = ", ".join(f"{o}x{n}" for o, n in self.ops) or "-"
        return f"ops[{ops}] consts{list(self.consts)}"


# ====================================================== python extract
class _PyPrinter:
    """Collect one term-group fingerprint from python assignment
    expressions."""

    def __init__(self):
        self.consts: List[float] = []
        self.ops: Dict[str, int] = {}

    def feed(self, node) -> None:
        self._walk(node)

    def _op(self, name: str) -> None:
        self.ops[name] = self.ops.get(name, 0) + 1

    def _walk(self, node) -> None:
        if node is None:
            return
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, float)) \
                    and not isinstance(node.value, bool):
                self.consts.append(float(node.value))
            return
        if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                        ast.USub):
            # fold -1.0 into a constant; keep neg as an op otherwise
            if isinstance(node.operand, ast.Constant) and isinstance(
                    node.operand.value, (int, float)):
                self.consts.append(-float(node.operand.value))
                return
            self._op("neg")
            self._walk(node.operand)
            return
        if isinstance(node, ast.BinOp):
            opname = {ast.Add: "add", ast.Sub: "sub", ast.Mult: "mul",
                      ast.Div: "div", ast.Pow: "pow"}.get(
                          type(node.op))
            if opname:
                self._op(opname)
            self._walk(node.left)
            self._walk(node.right)
            return
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            last = name.rsplit(".", 1)[-1]
            if not last and isinstance(node.func, ast.Attribute):
                # method on a non-trivial expression, e.g.
                # `(a + b).astype(f32)` — _dotted can't chain it
                last = node.func.attr
            if last in _CAST_CALLS:
                # transparent: f32(20.0) -> 20.0, x.astype(f32) -> x
                if isinstance(node.func, ast.Attribute) \
                        and last == "astype":
                    self._walk(node.func.value)
                    return
                for a in node.args:
                    self._walk(a)
                return
            args = node.args
            if last in _COND_CALLS and args:
                args = args[1:]          # drop the condition
            for a in args:
                self._walk(a)
            for kw in node.keywords:
                if kw.arg not in ("axis", "keepdims", "dtype",
                                  "num_keys", "mode"):
                    self._walk(kw.value)
            return
        if isinstance(node, ast.Subscript):
            # indexing is layout plumbing, not scoring structure
            self._walk(node.value)
            return
        if isinstance(node, (ast.Name, ast.Attribute, ast.Compare,
                             ast.BoolOp)):
            # leaves and conditions are excluded by design
            return
        if isinstance(node, ast.IfExp):
            self._walk(node.body)
            self._walk(node.orelse)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child)


def _collect_assigns(index: PackageIndex, fi: FuncInfo,
                     names: Tuple[str, ...], nested: bool
                     ) -> List[ast.AST]:
    out: List[ast.AST] = []
    keys = [fi.key]
    while keys:
        cur = index.functions[keys.pop(0)]
        for node in index._own_nodes(cur):
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name):
                tgt = node.target.id
            if tgt in names:
                out.append(node)
        if nested:
            keys.extend(cur.nested)
    return out


def _term_assignments(index: PackageIndex, fi: FuncInfo,
                      names: Tuple[str, ...]) -> List[ast.AST]:
    """Assignments to any of `names` in the site function INCLUDING its
    nested helper defs (kernel's spread lives in a nested
    `one_spread`); when a term is not defined there at all, climb the
    enclosing-def chain own-nodes-only (host's `pen_score` lives in
    host_solve_kernel's scope, one level above group_scores — own
    nodes only, so a sibling nested scorer is not double-collected)."""
    out = _collect_assigns(index, fi, names, nested=True)
    cur: Optional[FuncInfo] = fi
    while not out and cur is not None and cur.parent:
        cur = index.functions.get(cur.parent)
        if cur is None:
            break
        out = _collect_assigns(index, cur, names, nested=False)
    return out


def python_fingerprint(index: PackageIndex, fi: FuncInfo,
                       terms: Sequence[str]) -> Dict[str, TermPrint]:
    prints: Dict[str, TermPrint] = {}
    for term in terms:
        nodes = _term_assignments(index, fi, TERM_NAMES[term])
        if not nodes:
            continue
        p = _PyPrinter()
        for node in nodes:
            val = node.value
            p.feed(val)
            if isinstance(node, ast.AugAssign):
                p._op({ast.Add: "add", ast.Sub: "sub",
                       ast.Mult: "mul", ast.Div: "div"}.get(
                           type(node.op), "add"))
        prints[term] = TermPrint(
            consts=tuple(sorted(p.consts)),
            ops=tuple(sorted(p.ops.items())),
            const_set=tuple(sorted(set(p.consts))))
    return prints


# ====================================================== native extract
_C_FLOAT = re.compile(r"(?<![\w.])(-?\d+(?:\.\d*)?(?:e-?\d+)?)f?\b")
_C_STMT = re.compile(
    r"(?:const\s+)?(?:float|double|auto)?\s*"
    r"(?P<name>\w+)\s*(?P<aug>[+\-*/]?)=\s*(?P<rhs>[^;]+);")


def _c_statements(src: str) -> List[Tuple[str, str, str]]:
    """(name, augop, rhs) for every simple assignment statement, with
    line comments stripped and continuation lines joined."""
    src = re.sub(r"//[^\n]*", "", src)
    src = re.sub(r"\s+", " ", src)
    return [(m.group("name"), m.group("aug"), m.group("rhs"))
            for m in _C_STMT.finditer(src)]


def _c_normalize(rhs: str) -> str:
    """Translate C++ scoring idioms onto the python canonical form."""
    # subscripts are plumbing: strip [...] including nested ones
    prev = None
    while prev != rhs:
        prev = rhs
        rhs = re.sub(r"\[[^\[\]]*\]", "", rhs)
    # bool->float coercions fold away like implicit casts
    rhs = re.sub(r"\(\s*\w+\s*\?\s*1\.0f?\s*:\s*0\.0f?\s*\)", "B", rhs)
    # clip spelled as min(max(x, lo), hi)
    rhs = re.sub(
        r"std::min\s*\(\s*std::max\s*\(([^,]+),([^)]+)\)\s*,([^)]+)\)",
        r"clip(\1,\2,\3)", rhs)
    rhs = rhs.replace("std::pow", "POW").replace("std::floor", "floor")
    rhs = rhs.replace("std::max", "MAXF").replace("std::min", "MINF")
    return rhs


def _c_term_print(stmts: List[Tuple[str, str, str]],
                  names: Tuple[str, ...], term: str) -> TermPrint:
    consts: List[float] = []
    ops: Dict[str, int] = {}

    def add_op(name, n=1):
        ops[name] = ops.get(name, 0) + n

    for name, aug, rhs in stmts:
        if name not in names:
            continue
        rhs = _c_normalize(rhs)
        # ternary: drop the condition (like where)
        if "?" in rhs:
            cond, _, branches = rhs.partition("?")
            rhs = branches.replace(":", " ")
        if aug:
            add_op({"+": "add", "-": "sub", "*": "mul",
                    "/": "div"}[aug])
        # constants (before op counting so signs bind to numbers)
        for m in _C_FLOAT.finditer(rhs):
            consts.append(float(m.group(1)))
        body = _C_FLOAT.sub("C", rhs)
        add_op("pow", body.count("POW"))
        body = body.replace("POW", "")
        # unary minus: only when no operand precedes it (start of the
        # expression or right after an opener/separator); a minus
        # after an operand is the binary sub counted below
        for m in re.finditer(r"(?:^|[(,?:=])\s*-\s*(?=[A-Za-z_(])",
                             body.strip()):
            add_op("neg")
        # binary ops: a token on each side
        for opch, opname in (("+", "add"), ("*", "mul"),
                             ("/", "div")):
            add_op(opname, len(re.findall(
                re.escape(opch) if opch != "+" else r"(?<!\+)\+(?!\+)",
                body)))
        # binary minus: preceded by an identifier/paren/constant
        add_op("sub", len(re.findall(r"(?<=[\w)C])\s*-\s*(?=[\w(C])",
                                     body)))
    # neg got double-counted as sub when preceded by '(' -> already
    # excluded by the lookbehind; pow args contribute their own consts
    zero = {k: v for k, v in ops.items() if v}
    return TermPrint(consts=tuple(sorted(consts)),
                     ops=tuple(sorted(zero.items())),
                     const_set=tuple(sorted(set(consts))))


def native_fingerprint(path: str,
                       terms: Sequence[str]) -> Dict[str, TermPrint]:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    # scope to the scoring region when the source carries the standard
    # section markers, so same-named scratch vars elsewhere (top-k
    # scratch `score`, commit loops) don't pollute the fingerprint
    lo = src.find("batched scoring")
    hi = src.find("per-group top-k")
    if 0 <= lo < hi:
        src = src[lo:hi]
    stmts = _c_statements(src)
    out: Dict[str, TermPrint] = {}
    for term in terms:
        tp = _c_term_print(stmts, TERM_NAMES[term], term)
        if tp.consts or tp.ops:
            out[term] = tp
    return out


# ============================================================== pass
def run_score_pass(index: PackageIndex, cfg: AnalysisConfig,
                   package_dir: Optional[str] = None
                   ) -> List[Finding]:
    sites = getattr(cfg, "scorer_sites", None) or DEFAULT_SCORER_SITES
    findings: List[Finding] = []
    prints: List[Tuple[ScorerSite, str, Dict[str, TermPrint],
                       str, int]] = []
    site_fn_patterns: List[str] = []
    for site in sites:
        terms = site.terms or DEFAULT_TERMS
        if site.kind == "python":
            site_fn_patterns.append(site.site)
            fkeys = index.match_funcs([site.site])
            if not fkeys:
                findings.append(Finding(
                    "SCORE603", "-", "-", site.backend, site.site, 0,
                    f"registered scorer site `{site.site}` "
                    f"(backend {site.backend}) resolves to nothing; "
                    "the cross-backend drift check is blind to this "
                    "backend",
                    hint="update the registry entry in "
                         "analysis/score_pass.py (or AnalysisConfig."
                         "scorer_sites) after renaming the scorer"))
                continue
            fi = index.functions[fkeys[0]]
            fp = python_fingerprint(index, fi, terms)
            prints.append((site, site.backend, fp, fi.path,
                           fi.node.lineno))
        else:
            path = site.site if os.path.isabs(site.site) else \
                os.path.join(package_dir or "", site.site)
            if not os.path.exists(path):
                findings.append(Finding(
                    "SCORE603", "-", "-", site.backend, site.site, 0,
                    f"registered native scorer source `{site.site}` "
                    "not found; the drift check is blind to the "
                    f"{site.backend} backend",
                    hint="fix the path in the scoring-site registry"))
                continue
            fp = native_fingerprint(path, terms)
            prints.append((site, site.backend, fp, site.site, 0))

    # ---- SCORE601: compare every backend against the reference
    if prints:
        ref_site, ref_name, ref_fp, ref_path, _ = prints[0]
        for site, backend, fp, path, line in prints[1:]:
            terms = site.terms or DEFAULT_TERMS
            for term in terms:
                a = ref_fp.get(term)
                b = fp.get(term)
                if a is None:
                    continue          # reference doesn't carry it
                if b is None:
                    findings.append(Finding(
                        "SCORE601", "-", backend, term, path, line,
                        f"backend `{backend}` is missing scoring term "
                        f"`{term}` (reference backend `{ref_name}` "
                        "carries it)",
                        hint="replicate the term float-order-exactly "
                             "or register the backend with an "
                             "explicit reduced term list"))
                    continue
                if term in CONST_SET_TERMS:
                    if set(a.const_set) != set(b.const_set):
                        findings.append(_drift(backend, term, path,
                                               line, a, b, ref_name,
                                               consts_only=True))
                elif (a.consts, a.ops) != (b.consts, b.ops):
                    findings.append(_drift(backend, term, path, line,
                                           a, b, ref_name))

    # ---- SCORE602: scoring-shaped arithmetic outside the registry
    for fkey, fi in sorted(index.functions.items()):
        base = fkey.split("#")[0]
        if any(fnmatch.fnmatchcase(base, p) or
               fnmatch.fnmatchcase(_parent_chain(index, fi), p)
               for p in site_fn_patterns):
            continue
        if fi.module.startswith("nomad_tpu.analysis"):
            continue
        for node in index._own_nodes(fi):
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            val = node.value
            used: Set[str] = set()
            for sub in ast.walk(val):
                if isinstance(sub, ast.Name) \
                        and sub.id in _COMPOSITE_NAMES:
                    used.add(sub.id)
                elif isinstance(sub, ast.Attribute) \
                        and sub.attr in _COMPOSITE_NAMES:
                    used.add(sub.attr)
            if len(used) >= 2:
                findings.append(Finding(
                    "SCORE602", fi.module, fi.qual,
                    "+".join(sorted(used)), fi.path, node.lineno,
                    "scoring-shaped arithmetic (combines "
                    f"{sorted(used)}) outside the registered scorer "
                    "sites; a term added here exists in ONE backend "
                    "only and the twins silently diverge",
                    hint="move the logic into the registered scorer "
                         "sites (all backends) and/or add the site to "
                         "the scoring registry in "
                         "analysis/score_pass.py"))
    return findings


def _parent_chain(index: PackageIndex, fi: FuncInfo) -> str:
    """module:qual of the OUTERMOST enclosing def, so nested helpers of
    a registered site count as inside it."""
    cur = fi
    while cur.parent and cur.parent in index.functions:
        cur = index.functions[cur.parent]
    return cur.key.split("#")[0]


def _drift(backend: str, term: str, path: str, line: int,
           a: TermPrint, b: TermPrint, ref: str,
           consts_only: bool = False) -> Finding:
    what = ("constant set" if consts_only
            else "float-op fingerprint")
    return Finding(
        "SCORE601", "-", backend, term, path, line,
        f"scoring term `{term}` {what} diverges between backend "
        f"`{backend}` ({b.describe()}) and reference `{ref}` "
        f"({a.describe()}); the twins are no longer float-order-"
        "identical and placements can differ per backend",
        hint="make the term's constants and op structure identical in "
             "every registered backend (see STATIC_ANALYSIS.md "
             "SCORE6xx for the canonicalization rules)")
