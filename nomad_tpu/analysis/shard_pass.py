"""Pass 4: SPMD partition safety (SHARD4xx).

The PR-5 mesh-resident solve pins node-axis NamedShardings and keeps
planes resident in HBM across calls; the PR-8 elastic tier remaps node
ownership by tile instead of contiguous blocks. Both turned up the
same family of silent-wrong-answer bugs: array ops that are value-
correct on one device but partition-UNSAFE once the operand is
sharded.

Rules
  SHARD401  scatter (`x.at[...].set/add`, or a scatter-helper such as
            kernel.delta_scatter_*) applied to a NamedSharding-sharded
            operand OUTSIDE a shard_map context. GSPMD is free to
            replicate the update and apply it once per shard — the
            historical double-applied-scatter class. Sharded operands
            must route through an owner-mapped shard_map scatter.
  SHARD402  ownership-mask-free scatter inside a shard_map body: an
            `x.at[idx].set/add(...)` without `mode="drop"`. Non-owned
            rows must be pinned out of range and dropped; without the
            mask, negative locals WRAP python-style and corrupt
            another shard's rows.
  SHARD403  contiguous-block axis arithmetic inside a shard_map body:
            ownership/locality derived with `//` or `%` from an
            axis-size expression (`x.shape[0]`, n_shards-like values).
            Correct for the static block layout, silently wrong under
            an elastic TileLayout remap — route global rows through
            the owner/slot tables instead.  (warn tier: heuristic)

Provenance of "sharded" comes from the dataflow engine: direct
`device_put(x, NamedSharding(...))`, return summaries of `_put_node`-
style hooks, and class attributes assigned from either — with
inherited methods bound to the concrete subclass, so a subclass that
pins shardings but inherits a plain-jit delta path is seen as the
hazard it is.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import AnalysisConfig, Finding, PackageIndex, _dotted
from .dataflow import (AttrFact, DataflowEngine, _at_scatter_base,
                       _linear_nodes, _param_list, _self_offset,
                       scatter_call_has_drop_mode)

# names that look like a shard/axis count when used as a `//`/`%`
# denominator inside a shard body
_AXIS_SIZE_NAMES = {"n_shards", "num_shards", "nshards", "n_shard",
                    "chips_per_host", "n_hosts", "npl", "np_local",
                    "tile_np", "shard_count", "world_size"}


def run_shard_pass(index: PackageIndex, cfg: AnalysisConfig,
                   engine: Optional[DataflowEngine] = None
                   ) -> List[Finding]:
    engine = engine or DataflowEngine(index, cfg)
    findings: List[Finding] = []
    findings += _shard401(index, cfg, engine)
    findings += _shard402_403(index, cfg, engine)
    return findings


# ------------------------------------------------------------ SHARD401
def _shard401(index: PackageIndex, cfg: AnalysisConfig,
              engine: DataflowEngine) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[str] = set()
    safe = engine.shard_safe()
    scatter_map = engine.scatter_map()

    def check_function(fkey: str, bound_cls: Optional[str],
                       facts: Optional[Dict[str, AttrFact]]) -> None:
        if fkey in safe:
            return
        fi = index.functions[fkey]
        env: Dict = {}
        for node in _linear_nodes(index, fi):
            if isinstance(node, ast.Assign):
                val = engine._eval(fi, node.value, env, bound_cls)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        env[t.id] = val
            if not isinstance(node, ast.Call):
                continue
            # direct x.at[...].set/add on a sharded operand
            base = _at_scatter_base(node)
            if base is not None:
                val = engine._eval(fi, base, env, bound_cls)
                if engine.value_is_sharded(val, facts):
                    _emit(findings, seen, fi, node.lineno,
                          _render(base), direct=True)
                continue
            # scatter-helper call with a sharded operand
            target = engine._resolve(fi, node, bound_cls)
            if target is None:
                continue
            positions = scatter_map.get(target)
            if not positions:
                continue
            off = _self_offset(index, target, node)
            for pos in positions:
                apos = pos - off
                if not (0 <= apos < len(node.args)):
                    continue
                val = engine._eval(fi, node.args[apos], env, bound_cls)
                if engine.value_is_sharded(val, facts):
                    _emit(findings, seen, fi, node.lineno,
                          _render(node.args[apos]), direct=False,
                          helper=target.split(":")[-1])

    # module-level functions (no attr facts)
    for fkey, fi in sorted(index.functions.items()):
        if fi.cls is None:
            check_function(fkey, None, None)
    # methods, bound to each concrete class that reaches them — an
    # inherited method is re-checked under every subclass, because the
    # subclass's _put_node/_delta overrides change what is sharded
    for ckey in sorted(index.classes):
        facts = engine.class_facts(ckey)
        for mname, fkey in engine._mro_methods(ckey).items():
            check_function(fkey, ckey, facts)
    return findings


def _render(node) -> str:
    d = _dotted(node)
    if d:
        return d
    if isinstance(node, ast.Subscript):
        b = _dotted(node.value)
        if b:
            return f"{b}[...]"
    return "<expr>"


def _emit(findings: List[Finding], seen: Set[str], fi, line: int,
          operand: str, direct: bool, helper: str = "") -> None:
    sym = operand
    key = f"{fi.key}:{line}:{sym}"
    if key in seen:
        return
    seen.add(key)
    via = "an `.at[...]` scatter" if direct else \
        f"scatter helper `{helper}`"
    findings.append(Finding(
        "SHARD401", fi.module, fi.qual, sym, fi.path, line,
        f"`{operand}` carries a NamedSharding but is updated through "
        f"{via} outside shard_map; GSPMD may replicate the update and "
        "apply it once per shard (the double-applied-scatter class)",
        hint="route the update through an owner-mapped shard_map "
             "scatter (each shard writes only rows it owns, "
             "mode=\"drop\"), or drop the sharding before the scatter"))


# ----------------------------------------------------- SHARD402 / 403
def _shard402_403(index: PackageIndex, cfg: AnalysisConfig,
                  engine: DataflowEngine) -> List[Finding]:
    findings: List[Finding] = []
    for root in sorted(engine.mesh_roots()):
        fi = index.functions.get(root)
        if fi is None:
            continue
        # the body itself plus directly nested defs (they trace inline)
        for fkey in [root] + list(fi.nested):
            sfi = index.functions[fkey]
            sizeish = _axis_size_locals(index, sfi)
            for node in index._own_nodes(sfi):
                if isinstance(node, ast.Call):
                    base = _at_scatter_base(node)
                    if base is not None and node.func.attr in (
                            "set", "add", "mul", "min", "max") \
                            and not scatter_call_has_drop_mode(node):
                        findings.append(Finding(
                            "SHARD402", sfi.module, sfi.qual,
                            _render(base), sfi.path, node.lineno,
                            f"scatter on `{_render(base)}` inside a "
                            "shard_map body without mode=\"drop\": "
                            "non-owned rows are not masked, and "
                            "negative locals WRAP python-style into "
                            "another shard's rows",
                            hint="pin non-owned indices to the dropped "
                                 "slot (e.g. local==Npl) and pass "
                                 "mode=\"drop\""))
                if isinstance(node, ast.BinOp) and isinstance(
                        node.op, (ast.FloorDiv, ast.Mod)):
                    why = _axis_size_expr(node.right, sizeish)
                    if why:
                        op = "//" if isinstance(node.op,
                                                ast.FloorDiv) else "%"
                        findings.append(Finding(
                            "SHARD403", sfi.module, sfi.qual,
                            f"{op}:{why}", sfi.path, node.lineno,
                            f"ownership arithmetic `{op} {why}` inside "
                            "a shard_map body assumes the contiguous "
                            "block layout; under an elastic TileLayout "
                            "remap slot order is not id order and the "
                            "derived owner/local is silently wrong",
                            hint="route global rows through the "
                                 "owner/slot tables (pass them in as "
                                 "operands) instead of deriving them "
                                 "from axis sizes"))
    return findings


def _axis_size_locals(index: PackageIndex, fi) -> Set[str]:
    """Local names bound to an axis-size-like expression."""
    out: Set[str] = set()
    for node in index._own_nodes(fi):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if _axis_size_expr(node.value, set()):
                out.add(node.targets[0].id)
    # parameters with axis-size names count too (closures over
    # n_shards/tile_np are the usual spelling)
    for name in _param_list(fi):
        if name.lower() in _AXIS_SIZE_NAMES:
            out.add(name)
    return out


def _axis_size_expr(node, sizeish: Set[str]) -> str:
    """Human-readable description when the expression is an axis-size
    source; '' otherwise."""
    if isinstance(node, ast.Subscript):
        b = node.value
        if isinstance(b, ast.Attribute) and b.attr == "shape":
            d = _dotted(b)
            return d or "shape[...]"
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d and (d.endswith("axis_size") or d.endswith("psum")):
            return d
    if isinstance(node, ast.Name):
        if node.id in sizeish or node.id.lower() in _AXIS_SIZE_NAMES:
            return node.id
    if isinstance(node, ast.Attribute):
        d = _dotted(node)
        if d and d.split(".")[-1].lower() in _AXIS_SIZE_NAMES:
            return d
    if isinstance(node, ast.BinOp):
        # N // n_shards and friends: size-of-size is still a size
        return (_axis_size_expr(node.left, sizeish)
                or _axis_size_expr(node.right, sizeish))
    return ""
