"""Dataflow layer for nomadlint v2.

The v1 passes are pure AST pattern matches over a call graph; every
bug class we have actually shipped and later caught by hand — the PR-5
zero-copy `device_put` aliasing double-charge, the GSPMD double-applied
scatter on a NamedSharding-sharded operand, the PR-4 donated-carry
read-after-dispatch — is a *dataflow* property: where a buffer came
from, whether a copy intervened, which call killed it. This module
adds exactly that layer, still pure `ast` (nothing analyzed is ever
imported):

  * per-function linear def-use scanning with buffer-identity
    provenance: a `BufferValue` tracks the identity sources of a value
    (parameters, `self` attributes), whether it crossed `device_put`,
    whether a NamedSharding was pinned, and whether a genuine copy
    (`np.array`, `.copy()`, fresh allocation) intervened —
    `np.asarray`/`ascontiguousarray` and dtype casts are
    identity-PRESERVING and propagate provenance unchanged;
  * interprocedural summaries (fixpoint with a recursion guard):
    return-value provenance (`_put_node`-style hooks advertise
    "returns a device buffer, copied, sharded"), transitive donation
    positions (a wrapper passing its parameter into a donated slot
    donates that parameter too, to any depth), and scatter positions
    (a parameter that flows into an `x.at[...].set/add` scatter);
  * class-level buffer facts with subclass-bound dispatch: methods are
    analyzed against the *concrete* class so an inherited
    `_put_node_side` picks up the subclass's `_put_node` override —
    this is what lets SHARD401 distinguish `ResidentSolver` (plain
    device buffers, plain jit scatter: fine) from a subclass that pins
    NamedSharding but forgets to reroute its delta scatters (the GSPMD
    double-apply).

The three v2 passes (shard_pass, alias_pass, score_pass) are queries
over this engine; the v1 passes keep their original machinery.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisConfig, FuncInfo, PackageIndex, _dotted

# -- call classification ------------------------------------------------
# identity-preserving wrappers: the result aliases the argument's buffer
PASSTHROUGH_SUFFIXES = (
    "asarray", "ascontiguousarray", "asanyarray", "atleast_1d",
    "atleast_2d", "ravel", "reshape", "view", "squeeze", "astype",
)
# genuine copies / fresh allocations: the result owns its buffer
COPY_SUFFIXES = (
    "array", "copy", "deepcopy", "zeros", "ones", "empty", "full",
    "zeros_like", "ones_like", "full_like", "empty_like", "arange",
    "stack", "concatenate", "vstack", "hstack", "tile", "repeat",
    "frombuffer", "fromiter", "linspace",
)
# single-argument cast wrappers that merely relabel a value
CAST_NAMES = {"f32", "i32", "u32", "float32", "float64", "int32",
              "int16", "int8", "uint32", "bool_", "int", "float"}
# in-place ndarray mutators (host-side writes through the buffer)
INPLACE_METHODS = {"fill", "sort", "put", "partition", "setfield",
                   "itemset", "resize", "setflags", "byteswap"}


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


@dataclasses.dataclass(frozen=True)
class BufferValue:
    """Provenance of one expression's value.

    atoms   identity sources still aliased by the value:
            "param:<name>" / "attr:<name>" (a `self` attribute).
            Empty for fresh/copied/opaque values.
    device  the value is (or contains) a device_put result
    sharded a NamedSharding was pinned somewhere on the way
    copied  a genuine copy separates the value from its atoms
    key     linear-scan expression key of the SOURCE buffer at the
            point of use ("t", "self._template") — used for
            order-sensitive same-function matching; None when the
            source is not a simple name/attr chain.
    """
    atoms: frozenset = frozenset()
    device: bool = False
    sharded: bool = False
    copied: bool = False
    key: Optional[str] = None

    @staticmethod
    def merge(vals: Sequence["BufferValue"]) -> "BufferValue":
        vals = [v for v in vals if v is not None]
        if not vals:
            return BufferValue()
        return BufferValue(
            atoms=frozenset().union(*[v.atoms for v in vals]),
            device=any(v.device for v in vals),
            sharded=any(v.sharded for v in vals),
            copied=all(v.copied for v in vals),
            key=vals[0].key if len(vals) == 1 else None)


@dataclasses.dataclass
class PutEvent:
    line: int
    src: BufferValue          # provenance of the device_put ARGUMENT
    sharded: bool             # NamedSharding pinned at this call
    stored_attr: Optional[str]   # `self.<a> = device_put(...)` target
    stored_name: Optional[str]   # `x = device_put(...)` target


@dataclasses.dataclass
class MutEvent:
    line: int
    target: BufferValue       # provenance of the mutated buffer
    desc: str                 # rendered mutation site ("x[...] = ")


@dataclasses.dataclass
class FuncDataflow:
    puts: List[PutEvent]
    mutations: List[MutEvent]
    attr_assigns: Dict[str, List[BufferValue]]   # self.<attr> = value
    returns: List[BufferValue]


@dataclasses.dataclass
class Summary:
    returns: BufferValue
    donates: Tuple[int, ...] = ()      # positional params donated
    scatter: Tuple[int, ...] = ()      # positional params scattered


@dataclasses.dataclass
class AttrFact:
    sharded: bool = False
    uncopied_puts: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)          # (fkey, line) device_put sites
    mutations: List[Tuple[str, int, str]] = dataclasses.field(
        default_factory=list)          # (fkey, line, desc)
    holds_param: bool = False          # aliases a caller-owned buffer


class DataflowEngine:
    def __init__(self, index: PackageIndex, cfg: AnalysisConfig):
        self.index = index
        self.cfg = cfg
        self._flow_cache: Dict[Tuple[str, Optional[str]],
                               FuncDataflow] = {}
        self._summary_cache: Dict[Tuple[str, Optional[str]],
                                  Summary] = {}
        self._in_progress: Set[Tuple[str, Optional[str]]] = set()
        self._class_facts: Dict[str, Dict[str, AttrFact]] = {}
        self._mesh_roots: Optional[Set[str]] = None
        self._shard_safe: Optional[Set[str]] = None
        self._donation: Optional[Dict[str, Tuple[int, ...]]] = None
        self._scatter_map: Optional[Dict[str, Tuple[int, ...]]] = None

    # ------------------------------------------------- mesh membership
    def mesh_roots(self) -> Set[str]:
        if self._mesh_roots is None:
            from .jit_pass import find_mesh_roots
            self._mesh_roots = set(find_mesh_roots(self.index))
        return self._mesh_roots

    def shard_safe(self) -> Set[str]:
        """Functions running under a shard_map/pmap context (roots plus
        everything reachable from them): scatters here see per-shard
        local blocks, not the global sharded operand."""
        if self._shard_safe is None:
            self._shard_safe = self.index.reachable(self.mesh_roots())
            self._shard_safe |= self.mesh_roots()
        return self._shard_safe

    # ----------------------------------------------- name/alias helpers
    def _full_name(self, fi: FuncInfo, node) -> str:
        d = _dotted(node)
        if not d:
            return ""
        head = d.split(".")[0]
        mi = self.index.modules[fi.module]
        la = self.index._local_imports(fi)
        target = la.get(head) or mi.aliases.get(head)
        return (target + d[len(head):]) if target else d

    def _is_device_put(self, fi: FuncInfo, call: ast.Call) -> bool:
        return self._full_name(fi, call.func).endswith("device_put")

    def _sharding_arg(self, fi: FuncInfo, call: ast.Call,
                      env: Dict[str, BufferValue],
                      shardy: Set[str]) -> bool:
        """Does this device_put pin a NamedSharding? (second positional
        arg or device=/sharding= kwarg that is a NamedSharding(...)
        call or a local bound to one)."""
        cands = list(call.args[1:]) + [
            kw.value for kw in call.keywords
            if kw.arg in ("device", "sharding", "out_shardings")]
        for c in cands:
            if isinstance(c, ast.Call) and self._full_name(
                    fi, c.func).endswith("NamedSharding"):
                return True
            if isinstance(c, ast.Name) and c.id in shardy:
                return True
        return False

    # ----------------------------------------------- expression values
    def _eval(self, fi: FuncInfo, node, env: Dict[str, BufferValue],
              bound_cls: Optional[str], depth: int = 0) -> BufferValue:
        """Provenance of an expression. Conservative: anything not
        understood is an opaque fresh-ish value with no atoms."""
        if depth > 12:
            return BufferValue()
        if isinstance(node, ast.Name):
            if node.id in env:
                v = env[node.id]
                return dataclasses.replace(v, key=node.id)
            params = _param_names(fi)
            if node.id in params:
                return BufferValue(atoms=frozenset({f"param:{node.id}"}),
                                   key=node.id)
            return BufferValue(key=node.id)
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d and d.startswith("self."):
                attr = d.split(".")[1]
                return BufferValue(atoms=frozenset({f"attr:{attr}"}),
                                   key=d)
            return BufferValue(key=d)
        if isinstance(node, ast.Subscript):
            # a subscript VIEW aliases the base buffer (numpy slicing)
            base = self._eval(fi, node.value, env, bound_cls, depth + 1)
            return dataclasses.replace(base, key=None)
        if isinstance(node, (ast.Tuple, ast.List)):
            return BufferValue.merge([
                self._eval(fi, e, env, bound_cls, depth + 1)
                for e in node.elts])
        if isinstance(node, ast.Dict):
            return BufferValue.merge([
                self._eval(fi, v, env, bound_cls, depth + 1)
                for v in node.values])
        if isinstance(node, ast.IfExp):
            return BufferValue.merge([
                self._eval(fi, node.body, env, bound_cls, depth + 1),
                self._eval(fi, node.orelse, env, bound_cls, depth + 1)])
        if isinstance(node, ast.BoolOp):
            return BufferValue.merge([
                self._eval(fi, v, env, bound_cls, depth + 1)
                for v in node.values])
        if isinstance(node, ast.Call):
            return self._eval_call(fi, node, env, bound_cls, depth)
        return BufferValue()

    def _eval_call(self, fi: FuncInfo, call: ast.Call,
                   env: Dict[str, BufferValue],
                   bound_cls: Optional[str], depth: int) -> BufferValue:
        full = self._full_name(fi, call.func)
        last = _last(full)
        # x.copy() / x.astype(...) method forms
        if isinstance(call.func, ast.Attribute) and not call.args \
                and call.func.attr == "copy":
            return BufferValue(copied=True)
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in PASSTHROUGH_SUFFIXES:
            # `x.astype(...)` (method: descend the receiver) vs
            # `np.asarray(x)` (module function: descend the argument)
            based = _dotted(call.func.value)
            head = based.split(".")[0] if based else ""
            la = self.index._local_imports(fi)
            mi = self.index.modules[fi.module]
            if head and (head in mi.aliases or head in la):
                if call.args:
                    return self._eval(fi, call.args[0], env, bound_cls,
                                      depth + 1)
                return BufferValue()
            return self._eval(fi, call.func.value, env, bound_cls,
                              depth + 1)
        if last in CAST_NAMES and len(call.args) == 1:
            return self._eval(fi, call.args[0], env, bound_cls,
                              depth + 1)
        if full.endswith("device_put"):
            src = (self._eval(fi, call.args[0], env, bound_cls,
                              depth + 1) if call.args else BufferValue())
            sharded = self._sharding_arg(fi, call, env, set())
            return BufferValue(atoms=src.atoms if not src.copied
                               else frozenset(),
                               device=True, sharded=sharded,
                               copied=src.copied)
        if last in PASSTHROUGH_SUFFIXES and call.args:
            return self._eval(fi, call.args[0], env, bound_cls,
                              depth + 1)
        if last in COPY_SUFFIXES:
            return BufferValue(copied=True)
        # internal call: substitute the callee's return summary
        target = self._resolve(fi, call, bound_cls)
        if target is not None:
            tfi = self.index.functions[target]
            tcls = (bound_cls if _is_self_call(call) and bound_cls
                    else (f"{tfi.module}:{tfi.cls}" if tfi.cls else None))
            ret = self.summary(target, tcls).returns
            if ret.atoms:
                # map "param:<name>" atoms through the argument list;
                # "attr:" atoms name the CALLEE's self and only survive
                # a self-call (same object)
                mapped: List[BufferValue] = []
                rest: Set[str] = set()
                pnames = _param_list(tfi)
                off = 1 if (tfi.cls is not None and pnames
                            and pnames[0] == "self") else 0
                for atom in ret.atoms:
                    if atom.startswith("param:"):
                        pname = atom[6:]
                        try:
                            pos = pnames.index(pname) - off
                        except ValueError:
                            pos = -1
                        arg = None
                        if 0 <= pos < len(call.args):
                            arg = call.args[pos]
                        for kw in call.keywords:
                            if kw.arg == pname:
                                arg = kw.value
                        if arg is not None:
                            mapped.append(self._eval(
                                fi, arg, env, bound_cls, depth + 1))
                            continue
                    elif atom.startswith("attr:") and _is_self_call(call):
                        rest.add(atom)
                base = BufferValue.merge(mapped) if mapped \
                    else BufferValue(copied=ret.copied)
                return BufferValue(
                    atoms=base.atoms | frozenset(rest),
                    device=ret.device or base.device,
                    sharded=ret.sharded or base.sharded,
                    copied=ret.copied and base.copied)
            return dataclasses.replace(ret, key=None)
        return BufferValue()

    def _resolve(self, fi: FuncInfo, call: ast.Call,
                 bound_cls: Optional[str]) -> Optional[str]:
        """resolve_call, with self-dispatch bound to the concrete
        class (subclass overrides win for inherited methods)."""
        if bound_cls and _is_self_call(call):
            target = self.index.method_on(bound_cls, call.func.attr)
            if target:
                return target
        la = self.index._local_imports(fi)
        lt = self.index._local_var_types(fi)
        return self.index.resolve_call(fi, call, la, lt)

    # -------------------------------------------------- per-func facts
    def flow(self, fkey: str,
             bound_cls: Optional[str] = None) -> FuncDataflow:
        ck = (fkey, bound_cls)
        cached = self._flow_cache.get(ck)
        if cached is not None:
            return cached
        fi = self.index.functions[fkey]
        env: Dict[str, BufferValue] = {}
        shardy: Set[str] = set()     # locals bound to NamedSharding(...)
        assigns: List[Tuple[int, str, str]] = []
        out = FuncDataflow([], [], {}, [])
        for node in _linear_nodes(self.index, fi):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and self._full_name(
                    fi, node.value.func).endswith("NamedSharding"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        shardy.add(t.id)
            if isinstance(node, ast.Call) and self._is_device_put(
                    fi, node):
                src = (self._eval(fi, node.args[0], env, bound_cls)
                       if node.args else BufferValue())
                out.puts.append(PutEvent(
                    line=node.lineno, src=src,
                    sharded=self._sharding_arg(fi, node, env, shardy),
                    stored_attr=None, stored_name=None))
            mut = self._mutation(fi, node, env, bound_cls)
            if mut is not None:
                out.mutations.append(mut)
            if isinstance(node, ast.Assign):
                val = self._eval(fi, node.value, env, bound_cls)
                params = _param_names(fi)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        if t.id in params:
                            # a rebind of a PARAMETER is usually a
                            # conditional default fill (`if x is None:
                            # x = np.stack(...)`); the linear scan
                            # cannot see the branch, so the caller's
                            # buffer identity must survive the merge
                            val = BufferValue(
                                atoms=val.atoms
                                | frozenset({f"param:{t.id}"}),
                                device=val.device, sharded=val.sharded,
                                copied=False)
                        env[t.id] = val
                        assigns.append((node.lineno, "name", t.id))
                    elif isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self":
                        out.attr_assigns.setdefault(
                            t.attr, []).append(val)
                        assigns.append((node.lineno, "attr", t.attr))
            if isinstance(node, ast.Return) and node.value is not None:
                out.returns.append(
                    self._eval(fi, node.value, env, bound_cls))
        # attach `x = device_put(...)` / `self.a = device_put(...)`
        # storage targets (the Assign statement and the Call expression
        # are visited separately; match them up by line)
        for put in out.puts:
            for line, kind, name in assigns:
                if line == put.line:
                    if kind == "name":
                        put.stored_name = name
                    else:
                        put.stored_attr = name
        self._flow_cache[ck] = out
        return out

    def _mutation(self, fi: FuncInfo, node, env, bound_cls
                  ) -> Optional[MutEvent]:
        """In-place HOST mutation through a buffer: subscript stores,
        augmented assigns, and the in-place ndarray method calls."""
        targets: List = []
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets
                       if isinstance(t, ast.Subscript)]
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) \
                and node.func.attr in INPLACE_METHODS:
            base = self._eval(fi, node.func.value, env, bound_cls)
            if base.atoms or base.key:
                return MutEvent(node.lineno, base,
                                f".{node.func.attr}()")
        elif isinstance(node, ast.Call) and self._full_name(
                fi, node.func).endswith("copyto") and node.args:
            base = self._eval(fi, node.args[0], env, bound_cls)
            if base.atoms or base.key:
                return MutEvent(node.lineno, base, "np.copyto")
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(t, ast.Name):
                continue        # plain rebind, not a mutation
            v = self._eval(fi, base, env, bound_cls)
            if v.atoms or v.key:
                return MutEvent(node.lineno, v, "subscript store")
        return None

    # ----------------------------------------------------- summaries
    def summary(self, fkey: str,
                bound_cls: Optional[str] = None) -> Summary:
        ck = (fkey, bound_cls)
        cached = self._summary_cache.get(ck)
        if cached is not None:
            return cached
        if ck in self._in_progress:         # recursion: stay opaque
            return Summary(BufferValue())
        self._in_progress.add(ck)
        try:
            fl = self.flow(fkey, bound_cls)
            ret = BufferValue.merge(fl.returns) if fl.returns \
                else BufferValue()
            summ = Summary(returns=ret,
                           donates=self.donation_map().get(fkey, ()),
                           scatter=self.scatter_map().get(fkey, ()))
        finally:
            self._in_progress.discard(ck)
        self._summary_cache[ck] = summ
        return summ

    # --------------------------------------------- donation (fixpoint)
    def donation_map(self) -> Dict[str, Tuple[int, ...]]:
        """fkey -> positional parameter indices whose buffers are dead
        after the call, to ANY wrapper depth: base case is the
        donate_argnums jit roots; a function passing its own parameter
        into a donated position donates that parameter too."""
        if self._donation is not None:
            return self._donation
        from .jit_pass import find_jit_roots
        donation: Dict[str, Set[int]] = {}
        for r in find_jit_roots(self.index):
            if r.donate:
                donation.setdefault(r.fkey, set()).update(r.donate)
        changed = True
        while changed:
            changed = False
            for fkey, fi in self.index.functions.items():
                pnames = _param_list(fi)
                if not pnames:
                    continue
                for call, target in self._resolved_calls(fkey):
                    tpos = donation.get(target)
                    if not tpos:
                        continue
                    off = _self_offset(self.index, target, call)
                    for pos in tpos:
                        apos = pos - off
                        if not (0 <= apos < len(call.args)):
                            continue
                        arg = call.args[apos]
                        if isinstance(arg, ast.Name) \
                                and arg.id in pnames:
                            ppos = pnames.index(arg.id)
                            cur = donation.setdefault(fkey, set())
                            if ppos not in cur:
                                cur.add(ppos)
                                changed = True
        self._donation = {k: tuple(sorted(v))
                          for k, v in donation.items()}
        return self._donation

    # ---------------------------------------------- scatter (fixpoint)
    def scatter_map(self) -> Dict[str, Tuple[int, ...]]:
        """fkey -> positional parameter indices that receive an
        `x.at[...].set/add` scatter (directly or transitively) OUTSIDE
        a shard_map context. Mesh-rooted functions are excluded: their
        scatters act on per-shard local blocks and are partition-safe
        by construction."""
        if self._scatter_map is not None:
            return self._scatter_map
        safe = self.shard_safe()
        scatter: Dict[str, Set[int]] = {}
        # config-registered helpers (e.g. kernel.delta_scatter_set
        # whose jit body is built dynamically and defeats resolution)
        for spec in getattr(self.cfg, "scatter_helpers", ()):
            name, _, pos = spec.partition("@")
            if name in self.index.functions:
                scatter.setdefault(name, set()).add(
                    int(pos) if pos else 0)
        for fkey, fi in self.index.functions.items():
            if fkey in safe:
                continue
            pnames = _param_list(fi)
            for node in self.index._own_nodes(fi):
                tgt = _at_scatter_base(node)
                if tgt is not None and isinstance(tgt, ast.Name) \
                        and tgt.id in pnames:
                    scatter.setdefault(fkey, set()).add(
                        pnames.index(tgt.id))
        changed = True
        while changed:
            changed = False
            for fkey, fi in self.index.functions.items():
                if fkey in safe:
                    continue
                pnames = _param_list(fi)
                if not pnames:
                    continue
                for call, target in self._resolved_calls(fkey):
                    tpos = scatter.get(target)
                    if not tpos:
                        continue
                    off = _self_offset(self.index, target, call)
                    for pos in tpos:
                        apos = pos - off
                        if not (0 <= apos < len(call.args)):
                            continue
                        arg = call.args[apos]
                        if isinstance(arg, ast.Name) \
                                and arg.id in pnames:
                            ppos = pnames.index(arg.id)
                            cur = scatter.setdefault(fkey, set())
                            if ppos not in cur:
                                cur.add(ppos)
                                changed = True
        self._scatter_map = {k: tuple(sorted(v))
                             for k, v in scatter.items()}
        return self._scatter_map

    def _resolved_calls(self, fkey: str):
        fi = self.index.functions[fkey]
        la = self.index._local_imports(fi)
        lt = self.index._local_var_types(fi)
        for node in self.index._own_nodes(fi):
            if isinstance(node, ast.Call):
                r = self.index.resolve_call(fi, node, la, lt)
                if r is not None:
                    yield node, r

    # ------------------------------------------------- class buffers
    def class_facts(self, ckey: str) -> Dict[str, AttrFact]:
        """Per-attribute buffer facts for one concrete class, with
        inherited methods analyzed under subclass-bound dispatch."""
        cached = self._class_facts.get(ckey)
        if cached is not None:
            return cached
        facts: Dict[str, AttrFact] = {}
        for mname, fkey in self._mro_methods(ckey).items():
            fl = self.flow(fkey, bound_cls=ckey)
            for attr, vals in fl.attr_assigns.items():
                fact = facts.setdefault(attr, AttrFact())
                for v in vals:
                    if v.sharded:
                        fact.sharded = True
                    if (not v.device and not v.copied
                            and any(a.startswith("param:")
                                    for a in v.atoms)):
                        fact.holds_param = True
            for put in fl.puts:
                if put.sharded:
                    continue        # sharded puts are SHARD territory
                if put.src.copied:
                    continue
                for atom in put.src.atoms:
                    if atom.startswith("attr:"):
                        facts.setdefault(
                            atom[5:], AttrFact()).uncopied_puts.append(
                            (fkey, put.line))
            for mut in fl.mutations:
                for atom in mut.target.atoms:
                    if atom.startswith("attr:"):
                        facts.setdefault(
                            atom[5:], AttrFact()).mutations.append(
                            (fkey, mut.line, mut.desc))
        # one propagation round: `self.b = self.a` shardedness
        for mname, fkey in self._mro_methods(ckey).items():
            fl = self.flow(fkey, bound_cls=ckey)
            for attr, vals in fl.attr_assigns.items():
                for v in vals:
                    for atom in v.atoms:
                        if atom.startswith("attr:") and facts.get(
                                atom[5:], AttrFact()).sharded:
                            facts.setdefault(attr,
                                             AttrFact()).sharded = True
        self._class_facts[ckey] = facts
        return facts

    def _mro_methods(self, ckey: str) -> Dict[str, str]:
        """name -> fkey over the class and its package bases, own
        definitions winning."""
        out: Dict[str, str] = {}
        seen: Set[str] = set()
        stack = [ckey]
        while stack:
            ck = stack.pop(0)
            if ck in seen or ck not in self.index.classes:
                continue
            seen.add(ck)
            ci = self.index.classes[ck]
            for name, fkey in ci.methods.items():
                out.setdefault(name, fkey)
            stack.extend(ci.bases)
        return out

    def value_is_sharded(self, val: BufferValue,
                         facts: Optional[Dict[str, AttrFact]]) -> bool:
        if val.sharded:
            return True
        if facts:
            for atom in val.atoms:
                if atom.startswith("attr:"):
                    f = facts.get(atom[5:])
                    if f is not None and f.sharded:
                        return True
        return False


# ---------------------------------------------------------- utilities
def _param_names(fi: FuncInfo) -> Set[str]:
    args = fi.node.args
    return set(_param_list(fi)) | {a.arg for a in args.kwonlyargs}


def _param_list(fi: FuncInfo) -> List[str]:
    args = fi.node.args
    return [a.arg for a in
            list(args.posonlyargs) + list(args.args)]


def _self_offset(index: PackageIndex, target: str,
                 call: ast.Call) -> int:
    """Positional shift between the callee's def params and the call's
    args when the callee is a method invoked through an instance."""
    tfi = index.functions.get(target)
    if tfi is None or tfi.cls is None:
        return 0
    if isinstance(call.func, ast.Attribute):
        return 1
    return 0


def _is_self_call(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "self")


def _linear_nodes(index: PackageIndex, fi: FuncInfo):
    """Own statements + expressions in source-line order (excludes
    nested def/class bodies, like PackageIndex._own_nodes, but sorted
    so the env scan sees defs before uses)."""
    nodes = list(index._own_nodes(fi))
    nodes.sort(key=lambda n: (getattr(n, "lineno", 0),
                              getattr(n, "col_offset", 0)))
    return nodes


def _at_scatter_base(node) -> Optional[ast.AST]:
    """`X.at[idx].set/add/...(rows)` -> the X expression, else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in (
            "set", "add", "mul", "min", "max", "apply", "get"):
        return None
    sub = f.value
    if not isinstance(sub, ast.Subscript):
        return None
    at = sub.value
    if isinstance(at, ast.Attribute) and at.attr == "at":
        return at.value
    return None


def scatter_call_has_drop_mode(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and kw.value.value == "drop":
            return True
    return False
