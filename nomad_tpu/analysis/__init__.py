"""nomadlint: static invariant analyzer for the nomad_tpu package.

Nine passes over a module-level call graph plus a dataflow layer
(def-use chains, buffer-identity provenance, interprocedural
summaries — see dataflow.py). No analyzed module is ever imported:
everything is `ast` on source text, so the analyzer runs without JAX
or a device.

  * FSM determinism (fsm_pass):   the raft apply path must be
    bit-deterministic across replicas — no wall clock, no randomness,
    no unordered-set iteration feeding state writes, and no StateStore
    mutation reachable from outside the apply path.
  * jit purity / retrace hazards (jit_pass): functions traced under
    jax.jit / pallas must stay host-effect free; Python-branching jit
    params must be static; donated buffers must not be read after
    dispatch.
  * lock discipline (lock_pass):  shared attributes of the threaded
    server plane must be written under their class lock; racy getters,
    unlocked module-global mutation and lock-ordering cycles are
    flagged.
  * SPMD partition safety (shard_pass): no plain-jit scatters on
    NamedSharding-sharded operands (the GSPMD double-apply class), no
    ownership-mask-free scatters in shard_map bodies, no contiguous-
    block axis arithmetic that breaks under elastic TileLayout remaps.
  * buffer aliasing / donation lifetime (alias_pass): no host mutation
    of buffers that flowed uncopied into device_put (the PR-5 zero-
    copy double-charge), no reads through transitively-donated
    carries (sharpens JIT204 across wrapper layers).
  * scoring-spec conformance (score_pass): solver/score_spec.py is
    the single declarative scoring spec; the spec-driven backends
    (host twin, kernel twin) must defer every float op to it, the
    hand backends (shortlist _sl_eval, pallas fused pass, native C++)
    are fingerprinted per term and verified against the spec, term
    coverage is checked both ways, and scoring-shaped arithmetic
    outside the spec/registered sites is flagged.
  * swallowed exceptions (robust_pass): bare/broad except handlers in
    the recovery-critical planes (raft, rpc, server, parallel, solver)
    must re-raise, use the bound error, or surface it through
    logging/metrics — silent drops turn injected faults (chaos plane,
    ISSUE 14) into undetected state divergence.
  * observability hygiene (obs_pass): metric/series names must be
    lowercase dotted paths under a registered namespace (OBS801);
    names built at runtime are unbounded-cardinality hazards (OBS802,
    warn) that must carry a baseline justification naming the bound.
  * lockset race detection (race_pass): interprocedural Eraser-style
    guarded-by inference over the scale-out control plane — shared
    attributes reachable from ≥2 thread roots must keep a non-empty
    lock intersection over their writes (RACE901/902), check-then-act
    windows are flagged (RACE903, warn), and no hot-path lock may be
    held across a blocking call — device solve, fsync, RPC, waits
    (LOCK305).

Checked-in suppressions live in baseline.toml next to this file; every
entry must carry a non-empty justification. Run `python -m
nomad_tpu.analysis`; exit 0 means zero unsuppressed findings (exit 3:
warn-tier only; exit 2: baseline error — see __main__).
See STATIC_ANALYSIS.md at the repo root for the rule catalog.
"""
from __future__ import annotations

import os
from typing import List, Optional

from .core import (AnalysisConfig, Finding, PackageIndex, Report,
                   pass_of, severity_of)
from .baseline import Baseline, BaselineError, load_baseline

ANALYZER_VERSION = "4.1"

# the directory CONTAINING the nomad_tpu package (analysis/ -> pkg -> root)
_PKG_DIR = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.toml")


def analyze(package_dir: Optional[str] = None,
            package_name: str = "nomad_tpu",
            baseline: Optional[Baseline] = None,
            use_baseline: bool = True,
            config: Optional[AnalysisConfig] = None,
            paths: Optional[List[str]] = None,
            cache_dir: Optional[str] = None) -> Report:
    """Run all passes; returns a Report with unsuppressed findings,
    suppressed count and the per-rule tally.

    `paths` switches on file-scoped INCREMENTAL mode (the CLI's
    `--paths`): the whole package is still indexed — cross-file facts
    like mesh-root reachability and spec reference fingerprints need
    the full call graph, so a partial index would manufacture false
    positives — but findings are limited to the named files, and the
    registry-rot/coverage rules (SCORE603/SCORE604) are muted because
    judging them is a whole-package statement, not a per-file one.
    CI must keep running without `paths`."""
    from .fsm_pass import run_fsm_pass
    from .jit_pass import run_jit_pass
    from .lock_pass import run_lock_pass
    from .shard_pass import run_shard_pass
    from .alias_pass import run_alias_pass
    from .score_pass import run_score_pass
    from .robust_pass import run_robust_pass
    from .obs_pass import run_obs_pass
    from .race_pass import run_race_pass
    from .dataflow import DataflowEngine

    package_dir = package_dir or _PKG_DIR
    cfg = config or AnalysisConfig()
    only_files = None
    if paths is not None:
        only_files = {
            os.path.normpath(os.path.relpath(os.path.abspath(p),
                                             os.path.abspath(package_dir)))
            for p in paths}
    index = PackageIndex.build(package_dir, package_name,
                               cache_dir=cache_dir)
    engine = DataflowEngine(index, cfg)
    findings: List[Finding] = []
    findings += run_fsm_pass(index, cfg)
    findings += run_jit_pass(index, cfg)
    findings += run_lock_pass(index, cfg)
    findings += run_shard_pass(index, cfg, engine)
    # alias pass sees prior findings so ALIAS502 never double-reports
    # a read JIT204 already covers
    findings += run_alias_pass(index, cfg, engine, prior=findings)
    findings += run_score_pass(index, cfg, package_dir=package_dir)
    findings += run_robust_pass(index, cfg)
    findings += run_obs_pass(index, cfg)
    # race pass sees prior findings so RACE901 never double-reports a
    # write LOCK301 already covers syntactically
    findings += run_race_pass(index, cfg, prior=findings)
    if only_files is not None:
        findings = [f for f in findings
                    if f.rule not in ("SCORE603", "SCORE604")
                    and os.path.normpath(f.path) in only_files]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    if baseline is None and use_baseline:
        path = default_baseline_path()
        if os.path.exists(path):
            baseline = load_baseline(path)
    return Report.build(findings, baseline, version=ANALYZER_VERSION)


__all__ = ["ANALYZER_VERSION", "AnalysisConfig", "Baseline",
           "BaselineError", "Finding", "PackageIndex", "Report",
           "analyze", "default_baseline_path", "load_baseline",
           "pass_of", "severity_of"]
