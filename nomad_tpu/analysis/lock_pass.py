"""Pass 3: lock discipline over the threaded server plane.

The server plane (RPC server, eval broker, plan applier, heartbeat,
drainer, raft node) shares per-class state across thread entry points.
Convention enforced here: a class that owns a lock guards ALL its
shared-attribute writes with it; helpers that rely on the caller
already holding the lock say so with a `_locked` name suffix; module
globals mutated at runtime are guarded by a module-level lock.

Rules
  LOCK301  self-attribute write outside the class lock in a
           lock-owning thread-shared class
  LOCK302  racy getter: a lockless method whose body just returns a
           lock-guarded attribute
  LOCK303  module-global mutated from function scope without a
           module-level lock held
  LOCK304  lock-ordering cycle (nested acquisitions in inconsistent
           order)

"Thread-shared" is a fixpoint over composition (ISSUE 6): a class that
starts threads/timers is shared, and so is every class reachable from a
shared class through constructor attribute types — controller state
objects (EWMA solve models, token buckets, admission counters) held by
the broker/worker/server are mutated from many threads even though they
never start one themselves, so they carry the same write discipline.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (AnalysisConfig, ClassInfo, Finding, PackageIndex,
                   _dotted, with_lock_names)

LOCK_FACTORIES = ("threading.Lock", "threading.RLock",
                  "threading.Condition", "threading.Semaphore",
                  "threading.BoundedSemaphore")


def _lock_attrs(index: PackageIndex, ci: ClassInfo) -> Set[str]:
    """self attrs assigned a threading.Lock/RLock/Condition anywhere in
    the class (usually __init__), plus the same on package bases."""
    out: Set[str] = set()
    stack = [ci.key]
    seen: Set[str] = set()
    while stack:
        ck = stack.pop()
        if ck in seen or ck not in index.classes:
            continue
        seen.add(ck)
        c = index.classes[ck]
        mi = index.modules[c.module]
        for fkey in c.methods.values():
            fi = index.functions[fkey]
            for node in index._own_nodes(fi):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                d = _dotted(node.value.func)
                if not d:
                    continue
                head = d.split(".")[0]
                full = (mi.aliases.get(head) or head) + d[len(head):]
                if full in LOCK_FACTORIES:
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and isinstance(
                                t.value, ast.Name) and t.value.id == "self":
                            out.add(t.attr)
        stack.extend(c.bases)
    return out


def _is_multithreaded(index: PackageIndex, ci: ClassInfo) -> bool:
    """Does the class start threads/timers, or are its methods used as
    thread targets anywhere in the package?"""
    for fkey in ci.methods.values():
        fi = index.functions[fkey]
        for name, _ in index.external_calls(fkey):
            if name in ("threading.Thread", "threading.Timer"):
                return True
    return False


def _thread_shared_classes(index: PackageIndex) -> Set[str]:
    """Thread-starting classes plus the fixpoint of everything they
    hold by composition (constructor attr types): an instance hung off
    a threaded class is reached from its threads, so its state carries
    the same lock discipline whether or not it starts threads itself."""
    shared: Set[str] = {ck for ck, ci in index.classes.items()
                        if _is_multithreaded(index, ci)}
    changed = True
    while changed:
        changed = False
        for ck in sorted(shared):
            ci = index.classes.get(ck)
            if ci is None:
                continue
            # composition edges: scalar attrs AND list-of-instances
            # containers (a shard hung off a threaded broker is reached
            # from every dequeue thread — ISSUE 17)
            for tkey in list(ci.attr_types.values()) \
                    + list(ci.attr_elem_types.values()):
                if tkey in index.classes and tkey not in shared:
                    shared.add(tkey)
                    changed = True
    return shared


def _locked_regions(fi, lock_attrs: Set[str]):
    """Line spans covered by `with self.<lock>:` in this function."""
    spans = []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.With):
            continue
        for name in with_lock_names(node):
            if name.startswith("self.") and name[5:] in lock_attrs:
                spans.append((node.lineno, _end(node)))
    return spans


def _end(node) -> int:
    return getattr(node, "end_lineno", node.lineno) or node.lineno


def _in_spans(line: int, spans) -> bool:
    return any(a <= line <= b for a, b in spans)


def _in_scope(module: str, cfg: AnalysisConfig) -> bool:
    return any(module == p or module.startswith(p + ".")
               for p in cfg.lock_module_prefixes)


def run_lock_pass(index: PackageIndex, cfg: AnalysisConfig
                  ) -> List[Finding]:
    findings: List[Finding] = []
    lock_owners: Dict[str, Set[str]] = {}
    for ck, ci in index.classes.items():
        attrs = _lock_attrs(index, ci)
        if attrs:
            lock_owners[ck] = attrs

    # ---- LOCK301: unlocked self-attr writes in thread-shared lock
    # owners (started threads OR reached by composition from one)
    thread_shared = _thread_shared_classes(index)
    for ck, locks in sorted(lock_owners.items()):
        ci = index.classes[ck]
        if not _in_scope(ci.module, cfg):
            continue
        if ck not in thread_shared:
            continue
        guarded = _guarded_attrs(index, ci, locks)
        for mname, fkey in sorted(ci.methods.items()):
            if mname == "__init__" or mname.endswith("_locked"):
                continue
            fi = index.functions[fkey]
            spans = _locked_regions(fi, locks)
            for node in index._own_nodes(fi):
                tgt = _self_attr_write(node)
                if tgt is None:
                    continue
                attr, line = tgt
                if attr in locks:
                    continue
                if _in_spans(line, spans):
                    continue
                findings.append(Finding(
                    "LOCK301", ci.module, f"{ci.name}.{mname}", attr,
                    ci.path, line,
                    f"`self.{attr}` is written outside "
                    f"{_lock_label(locks)} in multithreaded class "
                    f"{ci.name}",
                    hint="move the write under the lock, or rename "
                         "the method with a `_locked` suffix if the "
                         "caller is documented to hold it"))
            _ = guarded  # (used by LOCK302 below; kept for symmetry)

    # ---- LOCK301 (sharded containers, ISSUE 17): a write that reaches
    # an ELEMENT of a lock-owning class through a subscripted container
    # (`self._shards[i].attr = v`) must hold the element's OWN lock —
    # the owning class's lock (if any) does not guard shard state
    for ck in sorted(thread_shared):
        ci = index.classes.get(ck)
        if ci is None or not _in_scope(ci.module, cfg):
            continue
        for cont, elem_key in sorted(ci.attr_elem_types.items()):
            elem_locks = lock_owners.get(elem_key)
            if not elem_locks:
                continue
            elem_name = index.classes[elem_key].name
            for mname, fkey in sorted(ci.methods.items()):
                if mname == "__init__" or mname.endswith("_locked"):
                    continue
                fi = index.functions[fkey]
                spans = _elem_locked_regions(fi, cont, elem_locks)
                for node in index._own_nodes(fi):
                    w = _subscript_attr_write(node)
                    if w is None:
                        continue
                    wcont, attr, line = w
                    if wcont != cont or attr in elem_locks:
                        continue
                    if _in_spans(line, spans):
                        continue
                    findings.append(Finding(
                        "LOCK301", ci.module, f"{ci.name}.{mname}",
                        f"{cont}[].{attr}", ci.path, line,
                        f"`self.{cont}[...].{attr}` is written without "
                        f"the owning {elem_name} shard's "
                        f"{_lock_label(elem_locks)}; per-shard state "
                        "must be guarded by the element's own lock",
                        hint="wrap the write in `with "
                             f"self.{cont}[i].{sorted(elem_locks)[0]}:`"
                             " or route it through a shard method that "
                             "takes its lock"))

    # ---- LOCK302: racy getters
    for ck, locks in sorted(lock_owners.items()):
        ci = index.classes[ck]
        if not _in_scope(ci.module, cfg):
            continue
        guarded = _guarded_attrs(index, ci, locks)
        for mname, fkey in sorted(ci.methods.items()):
            if mname == "__init__" or mname.endswith("_locked"):
                continue
            fi = index.functions[fkey]
            if _locked_regions(fi, locks):
                continue
            body = [n for n in fi.node.body
                    if not isinstance(n, ast.Expr)
                    or not isinstance(n.value, ast.Constant)]
            if len(body) != 1 or not isinstance(body[0], ast.Return):
                continue
            ret = body[0].value
            attr = None
            for sub in ast.walk(ret) if ret is not None else ():
                if isinstance(sub, ast.Attribute) and isinstance(
                        sub.value, ast.Name) and sub.value.id == "self":
                    attr = sub.attr
                    break
            if attr and attr in guarded and attr not in locks:
                findings.append(Finding(
                    "LOCK302", ci.module, f"{ci.name}.{mname}", attr,
                    ci.path, body[0].lineno,
                    f"lockless getter returns `self.{attr}`, which is "
                    f"written under {_lock_label(locks)} elsewhere; "
                    "readers can observe torn/stale state",
                    hint="take the lock for the read (cheap, and makes "
                         "the memory-visibility contract explicit)"))

    # ---- LOCK303: module-global mutation without a module lock
    for fkey, fi in sorted(index.functions.items()):
        mi = index.modules[fi.module]
        if not _in_scope(fi.module, cfg):
            continue
        module_locks = _module_locks(index, fi.module)
        spans = _module_lock_spans(fi, module_locks)
        gdecl = {n for node in index._own_nodes(fi)
                 if isinstance(node, ast.Global) for n in node.names}
        for node in index._own_nodes(fi):
            name, line = _global_write(node, mi.globals, gdecl) \
                or (None, 0)
            if name is None:
                continue
            if _in_spans(line, spans):
                continue
            findings.append(Finding(
                "LOCK303", fi.module, fi.qual, name, fi.path, line,
                f"module global `{name}` is mutated from function "
                "scope without a module-level lock; concurrent "
                "callers race the write",
                hint="guard with a module-level threading.Lock "
                     "(double-checked if the write is a cache fill)"))

    # ---- LOCK304: lock-ordering cycles (syntactic nesting)
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for fkey, fi in sorted(index.functions.items()):
        if not _in_scope(fi.module, cfg):
            continue
        ci = index.class_of_func(fi)
        locks = lock_owners.get(ci.key) if ci else None
        if not locks:
            continue
        _collect_nesting(fi, ci, locks, edges)
    findings.extend(_report_cycles(index, edges))
    return findings


def _lock_label(locks: Set[str]) -> str:
    return " / ".join(f"self.{a}" for a in sorted(locks))


def _self_attr_write(node) -> Optional[Tuple[str, int]]:
    """(attr, line) when the node writes self.<attr> or a container
    reached through it (self.attr[...] = ...)."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = node.targets
    for t in targets:
        base = t
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute) and isinstance(
                base.value, ast.Name) and base.value.id == "self":
            return base.attr, node.lineno
    return None


def _subscript_attr_write(node) -> Optional[Tuple[str, str, int]]:
    """(container_attr, leaf_attr, line) for writes of the shape
    `self.<cont>[...].<attr> = v` (one subscript hop off self)."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = node.targets
    for t in targets:
        leaf = t
        while isinstance(leaf, ast.Subscript):
            leaf = leaf.value
        if not isinstance(leaf, ast.Attribute):
            continue
        sub = leaf.value
        if not isinstance(sub, ast.Subscript):
            continue
        base = sub.value
        if isinstance(base, ast.Attribute) and isinstance(
                base.value, ast.Name) and base.value.id == "self":
            return base.attr, leaf.attr, node.lineno
    return None


def _elem_locked_regions(fi, cont: str, elem_locks: Set[str]):
    """Line spans covered by `with self.<cont>[...].<lock>:` — the
    subscripted form with_lock_names can't render (its _dotted walker
    stops at a Subscript)."""
    spans = []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            ce = item.context_expr
            if not (isinstance(ce, ast.Attribute)
                    and ce.attr in elem_locks
                    and isinstance(ce.value, ast.Subscript)):
                continue
            base = ce.value.value
            if isinstance(base, ast.Attribute) and isinstance(
                    base.value, ast.Name) and base.value.id == "self" \
                    and base.attr == cont:
                spans.append((node.lineno, _end(node)))
    return spans


def _guarded_attrs(index: PackageIndex, ci: ClassInfo,
                   locks: Set[str]) -> Set[str]:
    """Attrs written under the class lock outside __init__ (i.e. state
    the class treats as lock-protected)."""
    out: Set[str] = set()
    for mname, fkey in ci.methods.items():
        if mname == "__init__":
            continue
        fi = index.functions[fkey]
        spans = _locked_regions(fi, locks)
        if not spans:
            continue
        for node in index._own_nodes(fi):
            w = _self_attr_write(node)
            if w and w[0] not in locks and _in_spans(w[1], spans):
                out.add(w[0])
    return out


def _module_locks(index: PackageIndex, module: str) -> Set[str]:
    """Module-level names assigned a threading.Lock()."""
    mi = index.modules[module]
    out: Set[str] = set()
    for node in mi.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            d = _dotted(node.value.func)
            if not d:
                continue
            head = d.split(".")[0]
            full = (mi.aliases.get(head) or head) + d[len(head):]
            if full in LOCK_FACTORIES:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _module_lock_spans(fi, module_locks: Set[str]):
    spans = []
    for node in ast.walk(fi.node):
        if isinstance(node, ast.With):
            for name in with_lock_names(node):
                if name in module_locks:
                    spans.append((node.lineno, _end(node)))
    return spans


def _global_write(node, module_globals: Set[str],
                  global_decls: Set[str]
                  ) -> Optional[Tuple[str, int]]:
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    for t in targets:
        base = t
        while isinstance(base, ast.Subscript):
            base = base.value
        if not isinstance(base, ast.Name):
            continue
        # container mutation through subscript reaches the shared
        # module object directly; a plain NAME rebinding only does so
        # under a `global` declaration (else it creates a local)
        if base is not t and base.id in module_globals:
            return base.id, node.lineno
        if base is t and base.id in global_decls:
            return base.id, node.lineno
    return None


def _collect_nesting(fi, ci, locks: Set[str],
                     edges: Dict[Tuple[str, str], Tuple[str, int]]
                     ) -> None:
    """Record (outer, inner) pairs for nested with-lock acquisitions."""
    def walk(node, held: List[str]):
        if isinstance(node, ast.With):
            acquired = [f"{ci.name}.{n[5:]}" for n in
                        with_lock_names(node)
                        if n.startswith("self.") and n[5:] in locks]
            for outer in held:
                for inner in acquired:
                    if outer != inner:
                        edges.setdefault((outer, inner),
                                         (fi.path, node.lineno))
            held = held + acquired
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue
            walk(child, held)

    walk(fi.node, [])


def _report_cycles(index: PackageIndex, edges) -> List[Finding]:
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    findings: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            cur, path = stack.pop()
            for nxt in sorted(graph.get(cur, ())):
                if nxt == start and len(path) > 1:
                    cyc = tuple(sorted(path))
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    where, line = edges[(cur, start)]
                    findings.append(Finding(
                        "LOCK304", "-", "-",
                        "->".join(path + [start]), where, line,
                        "lock-ordering cycle: "
                        + " -> ".join(path + [start])
                        + "; two threads taking these locks in "
                          "opposite order deadlock",
                        hint="impose a single acquisition order (or "
                             "collapse to one lock)"))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return findings
