"""Fit checking and bin-pack scoring — the inner arithmetic of placement.

Reference: nomad/structs/funcs.go `AllocsFit` :103, `ScoreFit` :155.
These host-side scalar versions are the golden semantics; the TPU solver
(nomad_tpu/solver/rank.py) vectorizes exactly this math and is differential-
tested against these functions.
"""
from __future__ import annotations

import math
from typing import List, Optional, Tuple

from .alloc import Allocation
from .node import Node
from .resources import ComparableResources
from .network import NetworkIndex
from .devices import DeviceAccounter

# Maximum achievable score: both dimensions completely free
# (20 - (10^0 + 10^0)) = 18. Reference: scheduler/rank.go:13.
BINPACK_MAX_FIT_SCORE = 18.0


def allocs_fit(node: Node, allocs: List[Allocation],
               net_idx: Optional[NetworkIndex] = None,
               check_devices: bool = False,
               ) -> Tuple[bool, str, ComparableResources]:
    """Would this set of allocations fit on the node?

    Returns (fit, exhausted_dimension, used). Semantics mirror
    reference funcs.go:103: terminal allocs are skipped; node reserved
    resources count as used; port collisions and bandwidth overcommit are
    network-dimension failures; device oversubscription optional.
    """
    used = ComparableResources()
    used.add(node.comparable_reserved_resources())
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        used.add(alloc.comparable_resources())

    ok, dim = node.comparable_resources().superset(used)
    if not ok:
        return False, dim, used

    if net_idx is None:
        net_idx = NetworkIndex()
        collide = net_idx.set_node(node) or net_idx.add_allocs(allocs)
        if collide:
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    if check_devices:
        acct = DeviceAccounter(node)
        if acct.add_allocs(allocs):
            return False, "device oversubscribed", used

    return True, "", used


def score_fit(node: Node, util: ComparableResources) -> float:
    """Google BestFit-v3 bin-pack score (reference funcs.go:155).

    0 (empty / overfit-clamped) .. 18 (perfectly packed). Higher is better:
    prefers filling nodes.
    """
    res = node.comparable_resources()
    reserved = node.comparable_reserved_resources()
    node_cpu = float(res.cpu) - float(reserved.cpu)
    node_mem = float(res.memory_mb) - float(reserved.memory_mb)
    if node_cpu <= 0 or node_mem <= 0:
        return 0.0

    free_pct_cpu = 1.0 - (float(util.cpu) / node_cpu)
    free_pct_mem = 1.0 - (float(util.memory_mb) / node_mem)

    total = math.pow(10, free_pct_cpu) + math.pow(10, free_pct_mem)
    score = 20.0 - total
    return max(0.0, min(BINPACK_MAX_FIT_SCORE, score))


def filter_terminal_allocs(allocs: List[Allocation]
                           ) -> Tuple[List[Allocation], dict]:
    """Split out server-terminal allocs; keep latest terminal per name.

    Reference: funcs.go FilterTerminalAllocs.
    """
    terminal_by_name = {}
    live = []
    for a in allocs:
        if a.terminal_status():
            prev = terminal_by_name.get(a.name)
            if prev is None or a.create_index > prev.create_index:
                terminal_by_name[a.name] = a
        else:
            live.append(a)
    return live, terminal_by_name


def generate_migrate_token(alloc_id: str, node_secret_id: str) -> str:
    """Token authorizing a REPLACEMENT alloc to read its previous
    alloc's ephemeral disk through the owning agent's fs API
    (reference: structs.GenerateMigrateToken — HMAC of the alloc id
    under the owning NODE's secret, so the serving agent can verify it
    without a server round trip)."""
    import base64
    import hashlib
    import hmac
    mac = hmac.new((node_secret_id or "").encode(),
                   alloc_id.encode(), hashlib.sha256).digest()
    return base64.urlsafe_b64encode(mac).decode().rstrip("=")


def compare_migrate_token(alloc_id: str, node_secret_id: str,
                          token: str) -> bool:
    """Constant-time migrate-token check (reference:
    structs.CompareMigrateToken)."""
    import hmac
    if not token:
        return False
    return hmac.compare_digest(
        generate_migrate_token(alloc_id, node_secret_id), token)
