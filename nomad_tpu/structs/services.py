"""Native service discovery registrations.

Reference: Nomad registers task services either into Consul
(command/agent/consul/) or — in later versions — into its own state as
native service discovery (the /v1/services surface). The TPU build
implements the NATIVE form: registrations are derived server-side from
alloc/task state transitions (deterministic in the FSM, so every
replica holds the same catalog) and served from /v1/services with
blocking-query indexes. Health mirrors task liveness; script/http
check execution stays a client-side concern (checks are parsed and
carried, not yet executed)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List


@dataclass
class ServiceRegistration:
    id: str = ""                  # "<alloc_id>-<task>-<service>"
    service_name: str = ""
    namespace: str = "default"
    job_id: str = ""
    alloc_id: str = ""
    node_id: str = ""
    task: str = ""
    address: str = ""
    port: int = 0
    tags: List[str] = field(default_factory=list)
    healthy: bool = True
    create_index: int = 0
    modify_index: int = 0
