"""Field-level job diffs for dry-run planning.

Reference: nomad/structs/diff.go — Job.Diff walks the spec producing a
tree of {Added, Deleted, Edited, None} entries per field/object, which
`nomad plan` renders and scheduler/annotate.go attaches to dry-run
plans. One generic dataclass walker replaces the reference's
per-struct hand-rolled methods.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

DIFF_NONE = "None"
DIFF_ADDED = "Added"
DIFF_DELETED = "Deleted"
DIFF_EDITED = "Edited"

# job fields that never show in a diff (reference: diff.go filters the
# indexes, submit time and other machine-stamped fields)
_JOB_FILTER = {"id", "create_index", "modify_index", "job_modify_index",
               "version", "submit_time", "status", "stable",
               "status_description", "stop"}
_TG_FILTER = {"name"}
_TASK_FILTER = {"name"}


def _scalar(v: Any) -> bool:
    return v is None or isinstance(v, (str, int, float, bool))


def _fmt(v: Any) -> str:
    return "" if v is None else str(v)


def _field_diffs(old, new, filt) -> List[Dict]:
    """Flat scalar fields of a dataclass pair."""
    out: List[Dict] = []
    cls = type(old if old is not None else new)
    for f in dataclasses.fields(cls):
        if f.name in filt:
            continue
        ov = getattr(old, f.name, None) if old is not None else None
        nv = getattr(new, f.name, None) if new is not None else None
        if not (_scalar(ov) and _scalar(nv)):
            continue
        if ov == nv and old is not None and new is not None:
            continue
        if old is None:
            typ = DIFF_ADDED
        elif new is None:
            typ = DIFF_DELETED
        elif ov is None and nv is not None:
            typ = DIFF_ADDED
        elif ov is not None and nv is None:
            typ = DIFF_DELETED
        else:
            typ = DIFF_EDITED
        out.append({"Type": typ, "Name": f.name,
                    "Old": _fmt(ov), "New": _fmt(nv)})
    return sorted(out, key=lambda d: d["Name"])


def _object_diff(name: str, old, new) -> Optional[Dict]:
    """One nested object (constraint/affinity/spread/resources...)."""
    if old is None and new is None:
        return None
    fields = _field_diffs(old, new, set())
    if not fields:
        return None
    typ = (DIFF_ADDED if old is None else
           DIFF_DELETED if new is None else DIFF_EDITED)
    return {"Type": typ, "Name": name, "Fields": fields}


def _object_list_diffs(name: str, olds: list, news: list) -> List[Dict]:
    """Lists of spec objects matched by identity of their full field
    tuple (reference: diff.go's set-based primitiveObjectSetDiff)."""
    def key(o):
        return tuple(_fmt(getattr(o, f.name))
                     for f in dataclasses.fields(o) if _scalar(
                         getattr(o, f.name)))
    old_by = {key(o): o for o in olds or []}
    new_by = {key(o): o for o in news or []}
    out = []
    for k in old_by.keys() - new_by.keys():
        out.append(_object_diff(name, old_by[k], None))
    for k in new_by.keys() - old_by.keys():
        out.append(_object_diff(name, None, new_by[k]))
    return [d for d in out if d]


def task_diff(old, new) -> Dict:
    typ = (DIFF_ADDED if old is None else
           DIFF_DELETED if new is None else DIFF_EDITED)
    fields = _field_diffs(old, new, _TASK_FILTER)
    objects: List[Dict] = []
    o_res = getattr(old, "resources", None) if old else None
    n_res = getattr(new, "resources", None) if new else None
    res = _object_diff("Resources", o_res, n_res)
    if res:
        objects.append(res)
    for attr, label in (("constraints", "Constraint"),
                        ("affinities", "Affinity")):
        objects.extend(_object_list_diffs(
            label, getattr(old, attr, None) if old else [],
            getattr(new, attr, None) if new else []))
    # config is a free dict
    oc = getattr(old, "config", {}) if old else {}
    nc = getattr(new, "config", {}) if new else {}
    cfg = [{"Type": (DIFF_ADDED if k not in oc else
                     DIFF_DELETED if k not in nc else DIFF_EDITED),
            "Name": k, "Old": _fmt(oc.get(k)), "New": _fmt(nc.get(k))}
           for k in sorted(set(oc) | set(nc))
           if oc.get(k) != nc.get(k)]
    if cfg:
        objects.append({"Type": DIFF_EDITED, "Name": "Config",
                        "Fields": cfg})
    if typ == DIFF_EDITED and not fields and not objects:
        typ = DIFF_NONE
    return {"Type": typ,
            "Name": (new or old).name,
            "Fields": fields, "Objects": objects}


def task_group_diff(old, new) -> Dict:
    typ = (DIFF_ADDED if old is None else
           DIFF_DELETED if new is None else DIFF_EDITED)
    fields = _field_diffs(old, new, _TG_FILTER)
    objects: List[Dict] = []
    for attr, label in (("constraints", "Constraint"),
                        ("affinities", "Affinity"),
                        ("spreads", "Spread")):
        objects.extend(_object_list_diffs(
            label, getattr(old, attr, None) if old else [],
            getattr(new, attr, None) if new else []))
    for attr, label in (("ephemeral_disk", "EphemeralDisk"),
                        ("update", "Update"),
                        ("restart_policy", "RestartPolicy"),
                        ("reschedule_policy", "ReschedulePolicy"),
                        ("migrate", "Migrate")):
        d = _object_diff(label, getattr(old, attr, None) if old else None,
                         getattr(new, attr, None) if new else None)
        if d:
            objects.append(d)
    old_tasks = {t.name: t for t in (old.tasks if old else [])}
    new_tasks = {t.name: t for t in (new.tasks if new else [])}
    tasks = []
    for name in sorted(old_tasks.keys() | new_tasks.keys()):
        td = task_diff(old_tasks.get(name), new_tasks.get(name))
        if td["Type"] != DIFF_NONE:
            tasks.append(td)
    if typ == DIFF_EDITED and not fields and not objects and not tasks:
        typ = DIFF_NONE
    return {"Type": typ, "Name": (new or old).name,
            "Fields": fields, "Objects": objects, "Tasks": tasks}


def job_diff(old, new) -> Dict:
    """Top-level diff (reference: diff.go Job.Diff)."""
    if old is None and new is None:
        raise ValueError("nothing to diff")
    typ = (DIFF_ADDED if old is None else
           DIFF_DELETED if new is None else DIFF_EDITED)
    fields = _field_diffs(old, new, _JOB_FILTER)
    # datacenters as a primitive list
    odc = list(getattr(old, "datacenters", []) or []) if old else []
    ndc = list(getattr(new, "datacenters", []) or []) if new else []
    if odc != ndc:
        fields.append({"Type": DIFF_EDITED, "Name": "datacenters",
                       "Old": ",".join(odc), "New": ",".join(ndc)})
    objects: List[Dict] = []
    for attr, label in (("constraints", "Constraint"),
                        ("affinities", "Affinity"),
                        ("spreads", "Spread")):
        objects.extend(_object_list_diffs(
            label, getattr(old, attr, None) if old else [],
            getattr(new, attr, None) if new else []))
    old_tgs = {g.name: g for g in (old.task_groups if old else [])}
    new_tgs = {g.name: g for g in (new.task_groups if new else [])}
    tgs = []
    for name in sorted(old_tgs.keys() | new_tgs.keys()):
        gd = task_group_diff(old_tgs.get(name), new_tgs.get(name))
        if gd["Type"] != DIFF_NONE:
            tgs.append(gd)
    if typ == DIFF_EDITED and not fields and not objects and not tgs:
        typ = DIFF_NONE
    return {"Type": typ, "ID": (new or old).id,
            "Fields": fields, "Objects": objects, "TaskGroups": tgs}
