"""Node: a machine in the cluster.

Reference: nomad/structs/structs.go `Node` :1642 and
nomad/structs/node_class.go (ComputedClass hashing — the key that powers
feasibility memoization in the scheduler and, in this build, the host-side
cache for non-vectorizable constraint ops like regex/version).
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .consts import (NODE_SCHED_ELIGIBLE, NODE_STATUS_DOWN, NODE_STATUS_READY)
from .csi import CSIPluginNodeInfo
from .resources import NodeReservedResources, NodeResources, ComparableResources

UNIQUE_NAMESPACE = "unique."


def is_unique_key(key: str) -> bool:
    return key.startswith(UNIQUE_NAMESPACE)


@dataclass
class DriverInfo:
    detected: bool = False
    healthy: bool = False
    health_description: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)


@dataclass
class HostVolumeConfig:
    name: str = ""
    path: str = ""
    read_only: bool = False


@dataclass
class DrainStrategy:
    deadline_s: float = 0.0        # <=0: no deadline; -1: force
    ignore_system_jobs: bool = False
    force_deadline: float = 0.0    # absolute unix time when drain forces


@dataclass
class NodeEvent:
    message: str = ""
    subsystem: str = ""
    timestamp: float = 0.0
    details: Dict[str, str] = field(default_factory=dict)


@dataclass
class Node:
    id: str = ""
    secret_id: str = ""
    name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_resources: NodeResources = field(default_factory=NodeResources)
    reserved_resources: NodeReservedResources = field(default_factory=NodeReservedResources)
    links: Dict[str, str] = field(default_factory=dict)
    drivers: Dict[str, DriverInfo] = field(default_factory=dict)
    host_volumes: Dict[str, HostVolumeConfig] = field(default_factory=dict)
    # plugin id -> node-side CSI plugin info (reference:
    # structs.Node.CSINodePlugins, fingerprinted by the client)
    csi_node_plugins: Dict[str, CSIPluginNodeInfo] = field(
        default_factory=dict)
    status: str = NODE_STATUS_READY
    status_description: str = ""
    scheduling_eligibility: str = NODE_SCHED_ELIGIBLE
    drain: bool = False
    drain_strategy: Optional[DrainStrategy] = None
    events: List[NodeEvent] = field(default_factory=list)
    computed_class: str = ""
    status_updated_at: float = 0.0
    create_index: int = 0
    modify_index: int = 0

    # -- scheduling predicates (reference: structs.go Node.Ready) --
    def ready(self) -> bool:
        return (self.status == NODE_STATUS_READY and not self.drain
                and self.scheduling_eligibility == NODE_SCHED_ELIGIBLE)

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN

    def comparable_resources(self) -> ComparableResources:
        r = self.node_resources
        return ComparableResources(cpu=r.cpu, memory_mb=r.memory_mb,
                                   disk_mb=r.disk_mb, networks=list(r.networks))

    def comparable_reserved_resources(self) -> ComparableResources:
        r = self.reserved_resources
        return ComparableResources(cpu=r.cpu, memory_mb=r.memory_mb,
                                   disk_mb=r.disk_mb)

    # -- computed class (reference: node_class.go ComputeClass) --
    def compute_class(self) -> str:
        """Hash the non-unique scheduling-relevant identity of the node.

        Included (matching the reference's HashInclude whitelist): datacenter,
        node_class, attributes/meta minus `unique.*` keys, and the device
        inventory identity (vendor/type/name/attributes minus unique).
        """
        devices = sorted(
            (d.vendor, d.type, d.name,
             tuple(sorted((k, str(v)) for k, v in d.attributes.items()
                          if not is_unique_key(k))))
            for d in self.node_resources.devices)
        ident = {
            "datacenter": self.datacenter,
            "node_class": self.node_class,
            "attributes": sorted((k, v) for k, v in self.attributes.items()
                                 if not is_unique_key(k)),
            "meta": sorted((k, v) for k, v in self.meta.items()
                           if not is_unique_key(k)),
            "devices": devices,
        }
        digest = hashlib.blake2b(
            json.dumps(ident, sort_keys=True, default=str).encode(),
            digest_size=8).hexdigest()
        self.computed_class = f"v1:{digest}"
        return self.computed_class

    def stub(self) -> dict:
        return {
            "ID": self.id, "Name": self.name, "Datacenter": self.datacenter,
            "NodeClass": self.node_class, "Status": self.status,
            "SchedulingEligibility": self.scheduling_eligibility,
            "Drain": self.drain,
        }


def resolve_node_target(node: Node, target: str):
    """Resolve a constraint LTarget like "${attr.cpu.arch}" against a node.

    Returns (value, found). Reference: scheduler/feasible.go resolveTarget.
    """
    if not target.startswith("${") or not target.endswith("}"):
        return None, False
    inner = target[2:-1]
    if inner == "node.unique.id":
        return node.id, True
    if inner == "node.datacenter":
        return node.datacenter, True
    if inner == "node.unique.name":
        return node.name, True
    if inner == "node.class":
        return node.node_class, True
    if inner.startswith("attr."):
        key = inner[len("attr."):]
        if key in node.attributes:
            return node.attributes[key], True
        return None, False
    if inner.startswith("meta."):
        key = inner[len("meta."):]
        if key in node.meta:
            return node.meta[key], True
        return None, False
    if inner.startswith("driver."):
        # ${driver.<name>} / ${driver.attr.*}: driver-provided attributes are
        # folded into node.attributes by the client under the same key.
        if inner in node.attributes:
            return node.attributes[inner], True
        return None, False
    return None, False
