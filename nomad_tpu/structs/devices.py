"""Device instance accounting (reference: nomad/structs/devices.go).

Tracks which device instances (GPU/TPU/FPGA ids) are claimed by allocs on a
node so the scheduler/applier can detect oversubscription and the device
allocator can hand out free instance IDs.
"""
from __future__ import annotations

from typing import Dict, List, Tuple


class DeviceAccounterInstance:
    def __init__(self, instances: Dict[str, int]):
        # instance id -> use count (healthy instances start at 0)
        self.instances = instances

    def free_count(self) -> int:
        return sum(1 for c in self.instances.values() if c == 0)


class DeviceAccounter:
    def __init__(self, node) -> None:
        self.devices: Dict[Tuple[str, str, str], DeviceAccounterInstance] = {}
        for dev in node.node_resources.devices:
            insts = {inst.id: 0 for inst in dev.instances if inst.healthy}
            self.devices[dev.id_tuple()] = DeviceAccounterInstance(insts)

    def clone(self) -> "DeviceAccounter":
        c = object.__new__(DeviceAccounter)
        c.devices = {k: DeviceAccounterInstance(dict(v.instances))
                     for k, v in self.devices.items()}
        return c

    def add_allocs(self, allocs) -> bool:
        """Mark instances used by allocs; True if oversubscribed/collision."""
        collision = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for ad in tr.devices:
                    key = (ad.vendor, ad.type, ad.name)
                    acct = self.devices.get(key)
                    if acct is None:
                        continue
                    for inst_id in ad.device_ids:
                        if inst_id not in acct.instances:
                            continue
                        acct.instances[inst_id] += 1
                        if acct.instances[inst_id] > 1:
                            collision = True
        return collision

    def add_reserved(self, vendor: str, typ: str, name: str,
                     device_ids: List[str]) -> bool:
        """Mark instance ids used; True only on genuine double-claims.
        Unknown device groups / stale instance ids are skipped (reference
        devices.go AddReserved tolerates re-fingerprinted inventory)."""
        acct = self.devices.get((vendor, typ, name))
        if acct is None:
            return False
        collision = False
        for inst_id in device_ids:
            if inst_id not in acct.instances:
                continue
            acct.instances[inst_id] += 1
            if acct.instances[inst_id] > 1:
                collision = True
        return collision

    def free_instances(self, vendor: str, typ: str, name: str) -> List[str]:
        acct = self.devices.get((vendor, typ, name))
        if acct is None:
            return []
        return [i for i, c in acct.instances.items() if c == 0]
