"""Per-node port and bandwidth accounting.

Reference: nomad/structs/network.go `NetworkIndex` :43 — used by the
bin-pack ranker to offer networks and by the plan applier to re-verify.
Port picking is inherently discrete/host-side (SURVEY §7.3); the TPU solve
models bandwidth only and the applier does port fixup with this class.
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional, Set, Tuple

from .resources import NetworkResource, Port

MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 32000
MAX_RANDOM_ATTEMPTS = 20


class NetworkIndex:
    """Tracks used ports per IP and bandwidth per device on one node."""

    def __init__(self) -> None:
        self.avail_networks: List[NetworkResource] = []   # node's networks
        self.avail_bandwidth: Dict[str, int] = {}          # device -> mbits
        self.used_ports: Dict[str, Set[int]] = {}          # ip -> ports
        self.used_bandwidth: Dict[str, int] = {}           # device -> mbits

    def release(self) -> None:
        self.__init__()

    def clone(self) -> "NetworkIndex":
        c = NetworkIndex()
        c.avail_networks = list(self.avail_networks)
        c.avail_bandwidth = dict(self.avail_bandwidth)
        c.used_ports = {ip: set(s) for ip, s in self.used_ports.items()}
        c.used_bandwidth = dict(self.used_bandwidth)
        return c

    # -- building the index --
    def set_node(self, node) -> bool:
        """Register node networks + reserved ports. True on collision."""
        collide = False
        for n in node.node_resources.networks:
            if n.device:
                self.avail_networks.append(n)
                self.avail_bandwidth[n.device] = max(
                    self.avail_bandwidth.get(n.device, 0), n.mbits)
        reserved = node.reserved_resources.parsed_ports()
        for ip in {n.ip for n in self.avail_networks}:
            for port in reserved:
                if not self._add_used_port(ip, port):
                    collide = True
        return collide

    def add_allocs(self, allocs) -> bool:
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            for tr in alloc.allocated_resources.tasks.values():
                for net in tr.networks:
                    if self.add_reserved(net):
                        collide = True
            for net in alloc.allocated_resources.shared.networks:
                if self.add_reserved(net):
                    collide = True
        return collide

    def add_reserved(self, net: NetworkResource) -> bool:
        collide = False
        for p in list(net.reserved_ports) + list(net.dynamic_ports):
            if p.value and not self._add_used_port(net.ip, p.value):
                collide = True
        if net.device:
            self.used_bandwidth[net.device] = (
                self.used_bandwidth.get(net.device, 0) + net.mbits)
        return collide

    def _add_used_port(self, ip: str, port: int) -> bool:
        s = self.used_ports.setdefault(ip, set())
        if port in s:
            return False
        s.add(port)
        return True

    # -- queries --
    def overcommitted(self) -> bool:
        for dev, used in self.used_bandwidth.items():
            if used > self.avail_bandwidth.get(dev, 0):
                return True
        return False

    def yield_ip(self):
        for n in self.avail_networks:
            yield n

    # -- assignment (reference network.go:256 AssignNetwork) --
    def assign_network(self, ask: NetworkResource, seed: Optional[int] = None
                       ) -> Tuple[Optional[NetworkResource], str]:
        """Find an IP satisfying the ask; pick dynamic ports.

        Deterministic when `seed` given (replay-test determinism policy,
        SURVEY §7.3 score-tie note).
        """
        if not self.avail_networks:
            return None, "no networks available"
        err = "no networks available"
        for n in self.avail_networks:
            # bandwidth check
            avail = self.avail_bandwidth.get(n.device, 0)
            used = self.used_bandwidth.get(n.device, 0)
            if used + ask.mbits > avail:
                err = "bandwidth exceeded"
                continue
            used_set = self.used_ports.get(n.ip, set())
            # reserved ports must be free
            collision = False
            for p in ask.reserved_ports:
                if p.value in used_set:
                    collision = True
                    break
            if collision:
                err = "reserved port collision"
                continue
            # dynamic ports
            rng = random.Random(seed if seed is not None
                                else hash((n.ip, len(used_set))))
            taken = set(used_set) | {p.value for p in ask.reserved_ports}
            dyn_ports: List[Port] = []
            ok = True
            for p in ask.dynamic_ports:
                port = self._pick_dynamic(rng, taken)
                if port < 0:
                    ok = False
                    err = "dynamic port selection failed"
                    break
                taken.add(port)
                dyn_ports.append(Port(label=p.label, value=port, to=p.to,
                                      host_network=p.host_network))
            if not ok:
                continue
            offer = NetworkResource(
                mode=ask.mode, device=n.device, ip=n.ip, cidr=n.cidr,
                mbits=ask.mbits,
                reserved_ports=[Port(p.label, p.value, p.to, p.host_network)
                                for p in ask.reserved_ports],
                dynamic_ports=dyn_ports)
            return offer, ""
        return None, err

    @staticmethod
    def _pick_dynamic(rng: random.Random, taken: Set[int]) -> int:
        for _ in range(MAX_RANDOM_ATTEMPTS):
            port = rng.randint(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT)
            if port not in taken:
                return port
        # linear fallback scan
        for port in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT + 1):
            if port not in taken:
                return port
        return -1
