"""Resource model: what a node has, what a task asks for, what an alloc holds.

Semantics follow the reference domain model (reference: nomad/structs/structs.go
`Resources` :1969, `NodeResources` :2508, AllocatedResources family) but the
shape is re-designed for tensorization: every request/usage can be flattened to
a fixed-width numeric vector (see nomad_tpu/solver/tensorize.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple


@dataclass
class Port:
    label: str = ""
    value: int = 0
    to: int = 0
    host_network: str = ""


@dataclass
class NetworkResource:
    """One network ask/grant: bandwidth plus reserved/dynamic ports."""
    mode: str = "host"
    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: List[Port] = field(default_factory=list)
    dynamic_ports: List[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        return NetworkResource(
            mode=self.mode, device=self.device, cidr=self.cidr, ip=self.ip,
            mbits=self.mbits,
            reserved_ports=[replace(p) for p in self.reserved_ports],
            dynamic_ports=[replace(p) for p in self.dynamic_ports],
        )

    def port_labels(self) -> Dict[str, int]:
        out = {}
        for p in self.reserved_ports:
            out[p.label] = p.value
        for p in self.dynamic_ports:
            out[p.label] = p.value
        return out


@dataclass
class RequestedDevice:
    """A task's device ask, e.g. name="nvidia/gpu" count=2.

    Name may be "<vendor>/<type>/<model>", "<vendor>/<type>" or "<type>"
    (reference: nomad/structs/structs.go RequestedDevice.ID semantics).
    """
    name: str = ""
    count: int = 1
    constraints: list = field(default_factory=list)   # List[Constraint]
    affinities: list = field(default_factory=list)    # List[Affinity]

    def id_tuple(self) -> Tuple[str, str, str]:
        """(vendor, type, model) with empty strings for unspecified parts."""
        parts = self.name.split("/")
        if len(parts) == 1:
            return ("", parts[0], "")
        if len(parts) == 2:
            return (parts[0], parts[1], "")
        return (parts[0], parts[1], "/".join(parts[2:]))

    def matches(self, vendor: str, typ: str, model: str) -> bool:
        return device_pattern_matches(self.id_tuple(), (vendor, typ, model))


def device_pattern_matches(pattern: Tuple[str, str, str],
                           ident: Tuple[str, str, str]) -> bool:
    """Wildcard device matching: empty pattern parts match anything
    (reference: structs.RequestedDevice ID semantics)."""
    return all(not p or p == d for p, d in zip(pattern, ident))


@dataclass
class Resources:
    """A task's resource request (reference: structs.Resources)."""
    cpu: int = 100            # MHz
    memory_mb: int = 300
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[RequestedDevice] = field(default_factory=list)

    def copy(self) -> "Resources":
        return Resources(
            cpu=self.cpu, memory_mb=self.memory_mb, disk_mb=self.disk_mb,
            networks=[n.copy() for n in self.networks],
            devices=[RequestedDevice(d.name, d.count, list(d.constraints),
                                     list(d.affinities)) for d in self.devices],
        )

    def add(self, other: "Resources") -> None:
        self.cpu += other.cpu
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb
        self.networks.extend(n.copy() for n in other.networks)


@dataclass
class NodeDevice:
    id: str = ""
    healthy: bool = True
    health_description: str = ""
    locality: Optional[dict] = None  # e.g. {"pci_bus_id": "..."}


@dataclass
class NodeDeviceResource:
    """A device group on a node (reference: structs.NodeDeviceResource)."""
    vendor: str = ""
    type: str = ""
    name: str = ""            # model
    instances: List[NodeDevice] = field(default_factory=list)
    attributes: Dict[str, object] = field(default_factory=dict)

    def id_tuple(self) -> Tuple[str, str, str]:
        return (self.vendor, self.type, self.name)


@dataclass
class NodeResources:
    """Total resources a node fingerprinted (reference: structs.NodeResources)."""
    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[NodeDeviceResource] = field(default_factory=list)


@dataclass
class NodeReservedResources:
    """Resources the node operator carved out of the total."""
    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_host_ports: str = ""  # "80,443,8000-8100"

    def parsed_ports(self) -> List[int]:
        out: List[int] = []
        s = self.reserved_host_ports.strip()
        if not s:
            return out
        for part in s.split(","):
            part = part.strip()
            if "-" in part:
                lo, hi = part.split("-", 1)
                out.extend(range(int(lo), int(hi) + 1))
            elif part:
                out.append(int(part))
        return out


@dataclass
class AllocatedDeviceResource:
    vendor: str = ""
    type: str = ""
    name: str = ""
    device_ids: List[str] = field(default_factory=list)


@dataclass
class AllocatedTaskResources:
    cpu: int = 0
    memory_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[AllocatedDeviceResource] = field(default_factory=list)

    def add(self, other: "AllocatedTaskResources") -> None:
        self.cpu += other.cpu
        self.memory_mb += other.memory_mb
        self.networks.extend(n.copy() for n in other.networks)
        self.devices.extend(
            AllocatedDeviceResource(d.vendor, d.type, d.name, list(d.device_ids))
            for d in other.devices)


@dataclass
class AllocatedSharedResources:
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)


@dataclass
class AllocatedResources:
    """What an allocation actually holds, per task plus shared."""
    tasks: Dict[str, AllocatedTaskResources] = field(default_factory=dict)
    shared: AllocatedSharedResources = field(default_factory=AllocatedSharedResources)

    def comparable(self) -> "ComparableResources":
        c = ComparableResources()
        for tr in self.tasks.values():
            c.cpu += tr.cpu
            c.memory_mb += tr.memory_mb
            c.networks.extend(tr.networks)
            c.devices.extend(tr.devices)
        c.disk_mb = self.shared.disk_mb
        c.networks.extend(self.shared.networks)
        return c

    def copy(self) -> "AllocatedResources":
        out = AllocatedResources()
        for name, tr in self.tasks.items():
            t = AllocatedTaskResources(cpu=tr.cpu, memory_mb=tr.memory_mb)
            t.networks = [n.copy() for n in tr.networks]
            t.devices = [AllocatedDeviceResource(d.vendor, d.type, d.name,
                                                 list(d.device_ids))
                         for d in tr.devices]
            out.tasks[name] = t
        out.shared = AllocatedSharedResources(
            disk_mb=self.shared.disk_mb,
            networks=[n.copy() for n in self.shared.networks])
        return out


@dataclass
class ComparableResources:
    """Flattened resource totals used by fit checks and scoring
    (reference: structs.ComparableResources + funcs.go algebra)."""
    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[AllocatedDeviceResource] = field(default_factory=list)

    def add(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.cpu += other.cpu
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb
        self.networks.extend(other.networks)
        self.devices.extend(other.devices)

    def superset(self, other: "ComparableResources") -> Tuple[bool, str]:
        """Is self >= other in every dimension? Returns (ok, exhausted_dim)."""
        if self.cpu < other.cpu:
            return False, "cpu"
        if self.memory_mb < other.memory_mb:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        return True, ""
