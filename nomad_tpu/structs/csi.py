"""CSI volume + plugin model.

Reference: nomad/structs/csi.go — CSIVolume (:160 area) with
access/attachment modes and read/write claim sets, claim admission
(`WriteFreeClaims`, `ClaimWrite`/`ClaimRead`/`ClaimRelease`), and
CSIPlugin health aggregated from node fingerprints. The subset here
covers scheduling + claim lifecycle; external CSI controller RPCs are
out of scope (no real CSI drivers in this environment).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

ACCESS_SINGLE_NODE_READER = "single-node-reader-only"
ACCESS_SINGLE_NODE_WRITER = "single-node-writer"
ACCESS_MULTI_NODE_READER = "multi-node-reader-only"
ACCESS_MULTI_NODE_SINGLE_WRITER = "multi-node-single-writer"
ACCESS_MULTI_NODE_MULTI_WRITER = "multi-node-multi-writer"

ATTACH_FILE_SYSTEM = "file-system"
ATTACH_BLOCK_DEVICE = "block-device"

CLAIM_READ = "read"
CLAIM_WRITE = "write"


@dataclass
class CSIVolume:
    id: str = ""
    namespace: str = "default"
    name: str = ""
    plugin_id: str = ""
    access_mode: str = ACCESS_SINGLE_NODE_WRITER
    attachment_mode: str = ATTACH_FILE_SYSTEM
    # alloc id -> node id
    read_claims: Dict[str, str] = field(default_factory=dict)
    write_claims: Dict[str, str] = field(default_factory=dict)
    # populated from plugin health at read time
    schedulable: bool = True
    controller_required: bool = False
    create_index: int = 0
    modify_index: int = 0

    # -- claim admission (reference: csi.go WriteFreeClaims/ReadSchedulable)
    def read_schedulable(self) -> bool:
        return self.schedulable

    def write_free(self) -> bool:
        if self.access_mode in (ACCESS_SINGLE_NODE_READER,
                                ACCESS_MULTI_NODE_READER):
            return False
        if self.access_mode == ACCESS_MULTI_NODE_MULTI_WRITER:
            return True
        return len(self.write_claims) == 0

    def claim(self, mode: str, alloc_id: str, node_id: str) -> None:
        """Admit one claim or raise ValueError (the FSM applies this
        deterministically on every replica)."""
        if mode == CLAIM_READ:
            if not self.read_schedulable():
                raise ValueError(f"volume {self.id} not schedulable")
            self.read_claims[alloc_id] = node_id
            return
        if mode == CLAIM_WRITE:
            if not self.write_free() \
                    and alloc_id not in self.write_claims:
                raise ValueError(
                    f"volume {self.id} has no free write claims")
            self.write_claims[alloc_id] = node_id
            return
        raise ValueError(f"unknown claim mode {mode!r}")

    def release(self, alloc_id: str) -> None:
        self.read_claims.pop(alloc_id, None)
        self.write_claims.pop(alloc_id, None)

    def in_use(self) -> bool:
        return bool(self.read_claims or self.write_claims)


@dataclass
class CSIPluginNodeInfo:
    plugin_id: str = ""
    healthy: bool = True
    requires_controller: bool = False


@dataclass
class CSIPlugin:
    """Aggregated plugin health (reference: csi.go CSIPlugin — derived
    from node fingerprints, not raft-written directly)."""
    id: str = ""
    nodes_healthy: int = 0
    nodes_expected: int = 0
    controller_required: bool = False

    @property
    def healthy(self) -> bool:
        return self.nodes_healthy > 0


def aggregate_plugins(nodes) -> Dict[str, CSIPlugin]:
    out: Dict[str, CSIPlugin] = {}
    for n in nodes:
        for pid, info in getattr(n, "csi_node_plugins", {}).items():
            p = out.setdefault(pid, CSIPlugin(id=pid))
            p.nodes_expected += 1
            if info.healthy and not n.terminal_status():
                p.nodes_healthy += 1
            p.controller_required |= info.requires_controller
    return out
