"""Evaluation, Plan and Deployment: the units of scheduling work and output.

Reference: nomad/structs/structs.go `Evaluation` :8995, `Plan` :9288,
`PlanResult` :9462, `Deployment` :7734.
"""
from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .alloc import Allocation
from .consts import (ALLOC_DESIRED_EVICT, ALLOC_DESIRED_STOP,
                     DEPLOYMENT_STATUS_PAUSED, DEPLOYMENT_STATUS_RUNNING,
                     EVAL_STATUS_BLOCKED, EVAL_STATUS_CANCELLED,
                     EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
                     EVAL_STATUS_PENDING, EVAL_TRIGGER_FAILED_FOLLOW_UP,
                     EVAL_TRIGGER_QUEUED_ALLOCS, EVAL_TRIGGER_ROLLING_UPDATE)
from .job import Job
from ..utils.ids import generate_uuid


@dataclass
class Evaluation:
    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    priority: int = 50
    type: str = "service"            # scheduler type = job type
    triggered_by: str = ""
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait_until: float = 0.0          # unix time for delayed evals
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    failed_tg_allocs: Dict[str, object] = field(default_factory=dict)
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    annotate_plan: bool = False
    queued_allocations: Dict[str, int] = field(default_factory=dict)
    leader_ack: str = ""             # broker token
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time: float = 0.0
    modify_time: float = 0.0

    def terminal_status(self) -> bool:
        return self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
                               EVAL_STATUS_CANCELLED)

    def should_enqueue(self) -> bool:
        return self.status == EVAL_STATUS_PENDING

    def should_block(self) -> bool:
        return self.status == EVAL_STATUS_BLOCKED

    def make_plan(self, job: Optional[Job]) -> "Plan":
        p = Plan(eval_id=self.id, priority=self.priority, job=job)
        if job is not None:
            p.all_at_once = job.all_at_once
        return p

    def next_rolling_eval(self, wait_s: float) -> "Evaluation":
        return Evaluation(
            namespace=self.namespace, priority=self.priority, type=self.type,
            triggered_by=EVAL_TRIGGER_ROLLING_UPDATE, job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING, previous_eval=self.id,
            wait_until=_time.time() + wait_s)

    def create_blocked_eval(self, class_eligibility: Dict[str, bool],
                            escaped: bool, quota_reached: str) -> "Evaluation":
        """Reference: Evaluation.CreateBlockedEval."""
        return Evaluation(
            namespace=self.namespace, priority=self.priority, type=self.type,
            triggered_by=EVAL_TRIGGER_QUEUED_ALLOCS, job_id=self.job_id,
            job_modify_index=self.job_modify_index, status=EVAL_STATUS_BLOCKED,
            previous_eval=self.id, class_eligibility=dict(class_eligibility),
            escaped_computed_class=escaped, quota_limit_reached=quota_reached)

    def create_failed_follow_up_eval(self, wait_s: float) -> "Evaluation":
        return Evaluation(
            namespace=self.namespace, priority=self.priority, type=self.type,
            triggered_by=EVAL_TRIGGER_FAILED_FOLLOW_UP, job_id=self.job_id,
            job_modify_index=self.job_modify_index, status=EVAL_STATUS_PENDING,
            wait_until=_time.time() + wait_s, previous_eval=self.id)


@dataclass
class DeploymentState:
    """Per-task-group deployment progress (reference: structs.DeploymentState)."""
    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: List[str] = field(default_factory=list)
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_s: float = 0.0
    require_progress_by: float = 0.0


@dataclass
class Deployment:
    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_spec_modify_index: int = 0
    job_create_index: int = 0
    task_groups: Dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = "Deployment is running"
    create_index: int = 0
    modify_index: int = 0

    def active(self) -> bool:
        return self.status in (DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_PAUSED)

    def requires_promotion(self) -> bool:
        return any(s.desired_canaries > 0 and not s.promoted
                   for s in self.task_groups.values())

    def has_auto_promote(self) -> bool:
        states = [s for s in self.task_groups.values() if s.desired_canaries > 0]
        return bool(states) and all(s.auto_promote for s in states)

    def copy(self) -> "Deployment":
        d = Deployment(id=self.id, namespace=self.namespace, job_id=self.job_id,
                       job_version=self.job_version,
                       job_modify_index=self.job_modify_index,
                       job_spec_modify_index=self.job_spec_modify_index,
                       job_create_index=self.job_create_index,
                       status=self.status,
                       status_description=self.status_description,
                       create_index=self.create_index,
                       modify_index=self.modify_index)
        for k, s in self.task_groups.items():
            d.task_groups[k] = DeploymentState(
                auto_revert=s.auto_revert, auto_promote=s.auto_promote,
                promoted=s.promoted, placed_canaries=list(s.placed_canaries),
                desired_canaries=s.desired_canaries,
                desired_total=s.desired_total, placed_allocs=s.placed_allocs,
                healthy_allocs=s.healthy_allocs,
                unhealthy_allocs=s.unhealthy_allocs,
                progress_deadline_s=s.progress_deadline_s,
                require_progress_by=s.require_progress_by)
        return d


@dataclass
class DeploymentStatusUpdate:
    deployment_id: str = ""
    status: str = ""
    status_description: str = ""


@dataclass
class Plan:
    """The scheduler's proposed mutations (reference: structs.Plan :9288)."""
    eval_id: str = ""
    eval_token: str = ""
    priority: int = 50
    all_at_once: bool = False
    job: Optional[Job] = None
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = field(default_factory=list)
    annotations: Optional[dict] = None
    snapshot_index: int = 0

    def append_stopped_alloc(self, alloc: Allocation, desc: str,
                             client_status: str = "") -> None:
        a = _shallow_alloc_copy(alloc)
        a.desired_status = ALLOC_DESIRED_STOP
        a.desired_description = desc
        if client_status:
            a.client_status = client_status
        a.job = None  # normalized: job known from plan
        self.node_update.setdefault(alloc.node_id, []).append(a)

    def append_alloc(self, alloc: Allocation) -> None:
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_preempted_alloc(self, alloc: Allocation, preempting_id: str) -> None:
        a = _shallow_alloc_copy(alloc)
        a.desired_status = ALLOC_DESIRED_EVICT
        a.desired_description = f"Preempted by alloc ID {preempting_id}"
        a.preempted_by_allocation = preempting_id
        a.job = None
        self.node_preemptions.setdefault(alloc.node_id, []).append(a)

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and self.deployment is None and not self.deployment_updates)

    def normalize_allocations(self) -> None:
        """Strip job snapshots from stopped/preempted allocs (wire size)."""
        for allocs in self.node_update.values():
            for a in allocs:
                a.job = None
        for allocs in self.node_preemptions.values():
            for a in allocs:
                a.job = None


@dataclass
class PlanResult:
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional[Deployment] = None
    deployment_updates: List[DeploymentStatusUpdate] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and self.deployment is None and not self.deployment_updates)

    def full_commit(self, plan: Plan):
        """Returns (fully_committed, n_expected, n_actual)."""
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual, expected, actual


def _shallow_alloc_copy(alloc: Allocation) -> Allocation:
    import copy
    a = copy.copy(alloc)
    a.task_states = dict(alloc.task_states)
    return a
