"""Domain constants.

Mirrors the constant vocabulary of the reference control plane
(reference: nomad/structs/structs.go) so that states/statuses/trigger types are
wire-compatible with Nomad's API surface.
"""

# --- Job types (reference: nomad/structs/structs.go:3524 area) ---
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_CORE = "_core"

JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100
CORE_JOB_PRIORITY = JOB_MAX_PRIORITY * 2

# --- Node ---
NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

NODE_SCHED_ELIGIBLE = "eligible"
NODE_SCHED_INELIGIBLE = "ineligible"

# --- Allocation desired status ---
ALLOC_DESIRED_RUN = "run"
ALLOC_DESIRED_STOP = "stop"
ALLOC_DESIRED_EVICT = "evict"

# --- Allocation client status ---
ALLOC_CLIENT_PENDING = "pending"
ALLOC_CLIENT_RUNNING = "running"
ALLOC_CLIENT_COMPLETE = "complete"
ALLOC_CLIENT_FAILED = "failed"
ALLOC_CLIENT_LOST = "lost"

# Desired-status descriptions (reference generic_sched.go / reconcile.go)
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_RESCHEDULED = "alloc was rescheduled because it failed"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_IN_PLACE = "alloc updating in-place"
ALLOC_NODE_TAINTED = "alloc not needed as node is tainted"

# --- Evaluation ---
EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_PERIODIC_JOB = "periodic-job"
EVAL_TRIGGER_NODE_DRAIN = "node-drain"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_ALLOC_STOP = "alloc-stop"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"
EVAL_TRIGGER_DEPLOYMENT_WATCHER = "deployment-watcher"
EVAL_TRIGGER_DEPLOYMENT_PROMOTION = "deployment-promotion"
EVAL_TRIGGER_FAILED_FOLLOW_UP = "failed-follow-up"
EVAL_TRIGGER_MAX_PLANS = "max-plan-attempts"
EVAL_TRIGGER_RETRY_FAILED_ALLOC = "alloc-failure"
EVAL_TRIGGER_QUEUED_ALLOCS = "queued-allocs"
EVAL_TRIGGER_PREEMPTION = "preemption"
EVAL_TRIGGER_SCALING = "scaling"

# --- Deployments ---
DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_PAUSED = "paused"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"

DEPLOYMENT_DESC_NEWER_JOB = "Cancelled due to newer version of job"
DEPLOYMENT_DESC_FAILED_ALLOCS = "Failed due to unhealthy allocations"
DEPLOYMENT_DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
DEPLOYMENT_DESC_SUCCESSFUL = "Deployment completed successfully"
DEPLOYMENT_DESC_STOPPED_JOB = "Cancelled because job is stopped"
DEPLOYMENT_DESC_NEEDS_PROMOTION = "Deployment is running but requires manual promotion"
DEPLOYMENT_DESC_AUTO_PROMOTION = "Deployment is running pending automatic promotion"

# description attached to allocs stopped by a destructive update
ALLOC_UPDATING = "alloc is being updated due to job update"

# --- Constraint operands (reference: scheduler/feasible.go:671-706) ---
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_DISTINCT_PROPERTY = "distinct_property"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"
CONSTRAINT_SEMVER = "semver"
CONSTRAINT_SET_CONTAINS = "set_contains"
CONSTRAINT_SET_CONTAINS_ALL = "set_contains_all"
CONSTRAINT_SET_CONTAINS_ANY = "set_contains_any"
CONSTRAINT_ATTR_IS_SET = "is_set"
CONSTRAINT_ATTR_IS_NOT_SET = "is_not_set"

# --- Task states ---
TASK_STATE_PENDING = "pending"
TASK_STATE_RUNNING = "running"
TASK_STATE_DEAD = "dead"

TASK_STARTED = "Started"
TASK_TERMINATED = "Terminated"
TASK_KILLING = "Killing"
TASK_KILLED = "Killed"
TASK_RESTARTING = "Restarting"
TASK_NOT_RESTARTING = "Not Restarting"
TASK_RECEIVED = "Received"
TASK_FAILED_VALIDATION = "Failed Validation"
TASK_SETUP_FAILURE = "Setup Failure"
TASK_DRIVER_FAILURE = "Driver Failure"
TASK_LEADER_DEAD = "Leader Task Dead"

# --- Reschedule policy ---
RESCHEDULE_DELAY_CONSTANT = "constant"
RESCHEDULE_DELAY_EXPONENTIAL = "exponential"
RESCHEDULE_DELAY_FIBONACCI = "fibonacci"

# --- Restart policy ---
RESTART_POLICY_FAIL = "fail"
RESTART_POLICY_DELAY = "delay"

# --- Migrate / update defaults ---
DEFAULT_MIN_HEALTHY_TIME_S = 10.0
DEFAULT_HEALTHY_DEADLINE_S = 300.0
DEFAULT_PROGRESS_DEADLINE_S = 600.0

# Plan normalization
MAX_RETAINED_JOB_VERSIONS = 6

# Scheduler types that are built in
SCHEDULERS = (JOB_TYPE_SERVICE, JOB_TYPE_BATCH, JOB_TYPE_SYSTEM, JOB_TYPE_CORE)

DEFAULT_NAMESPACE = "default"
DEFAULT_REGION = "global"
