"""Core domain model for the TPU-native orchestrator.

This package is the rebuild's equivalent of the reference's nomad/structs —
see SURVEY.md §2.2. Everything schedulable flows through these types.
"""
from .consts import *  # noqa: F401,F403
from .resources import (AllocatedDeviceResource, AllocatedResources,
                        AllocatedSharedResources, AllocatedTaskResources,
                        ComparableResources, NetworkResource, NodeDevice,
                        NodeDeviceResource, NodeReservedResources,
                        NodeResources, Port, RequestedDevice, Resources)
from .node import (DrainStrategy, DriverInfo, HostVolumeConfig, Node,
                   NodeEvent, resolve_node_target, is_unique_key)
from .job import (Affinity, Artifact, Constraint, DispatchPayloadConfig,
                  EphemeralDisk, Job, LogConfig, MigrateStrategy,
                  ParameterizedJobConfig, PeriodicConfig, ReschedulePolicy,
                  RestartPolicy, Service, ServiceCheck, Spread, SpreadTarget,
                  Task, TaskGroup, Template, UpdateStrategy, VolumeMount,
                  VolumeRequest)
from .alloc import (AllocDeploymentStatus, AllocMetric, Allocation,
                    DesiredTransition, RescheduleEvent, RescheduleTracker,
                    TaskEvent, TaskState, alloc_name)
from .eval_plan import (Deployment, DeploymentState, DeploymentStatusUpdate,
                        Evaluation, Plan, PlanResult)
from .funcs import (BINPACK_MAX_FIT_SCORE, allocs_fit, filter_terminal_allocs,
                    score_fit)
from .network import NetworkIndex
from .devices import DeviceAccounter

from .csi import (ACCESS_MULTI_NODE_MULTI_WRITER, ACCESS_MULTI_NODE_READER,
                  ACCESS_MULTI_NODE_SINGLE_WRITER, ACCESS_SINGLE_NODE_READER,
                  ACCESS_SINGLE_NODE_WRITER, ATTACH_BLOCK_DEVICE,
                  ATTACH_FILE_SYSTEM, CLAIM_READ, CLAIM_WRITE, CSIPlugin,
                  CSIPluginNodeInfo, CSIVolume, aggregate_plugins)
