"""Job / TaskGroup / Task: the declarative workload spec.

Reference: nomad/structs/structs.go `Job` :3524, `TaskGroup` :5149,
`Task` :5781, `Constraint` :7237, `Affinity` :7359, `Spread` :7447.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .consts import (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY,
                     DEFAULT_NAMESPACE, DEFAULT_REGION, JOB_DEFAULT_PRIORITY,
                     JOB_STATUS_PENDING, JOB_TYPE_BATCH, JOB_TYPE_SERVICE,
                     JOB_TYPE_SYSTEM, RESCHEDULE_DELAY_EXPONENTIAL,
                     RESTART_POLICY_FAIL)
from .resources import NetworkResource, Resources


@dataclass
class Constraint:
    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="

    def key(self):
        return (self.ltarget, self.rtarget, self.operand)

    def __str__(self):
        return f"{self.ltarget} {self.operand} {self.rtarget}"


@dataclass
class Affinity:
    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="
    weight: float = 50.0  # in [-100, 100]


@dataclass
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass
class Spread:
    attribute: str = ""
    weight: float = 50.0
    spread_targets: List[SpreadTarget] = field(default_factory=list)


@dataclass
class RestartPolicy:
    attempts: int = 2
    interval_s: float = 1800.0
    delay_s: float = 15.0
    mode: str = RESTART_POLICY_FAIL


@dataclass
class ReschedulePolicy:
    """Reference: structs.ReschedulePolicy; defaults per job type."""
    attempts: int = 0
    interval_s: float = 0.0
    delay_s: float = 30.0
    delay_function: str = RESCHEDULE_DELAY_EXPONENTIAL
    max_delay_s: float = 3600.0
    unlimited: bool = True

    @staticmethod
    def default_service() -> "ReschedulePolicy":
        return ReschedulePolicy(attempts=0, interval_s=0, delay_s=30,
                                delay_function=RESCHEDULE_DELAY_EXPONENTIAL,
                                max_delay_s=3600, unlimited=True)

    @staticmethod
    def default_batch() -> "ReschedulePolicy":
        return ReschedulePolicy(attempts=1, interval_s=24 * 3600, delay_s=5,
                                delay_function="constant", max_delay_s=0,
                                unlimited=False)


@dataclass
class UpdateStrategy:
    """Rolling-update / canary config (reference: structs.UpdateStrategy)."""
    stagger_s: float = 30.0
    max_parallel: int = 0
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0
    progress_deadline_s: float = 600.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0

    def rolling(self) -> bool:
        return self.max_parallel > 0


@dataclass
class MigrateStrategy:
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0


@dataclass
class EphemeralDisk:
    sticky: bool = False
    size_mb: int = 300
    migrate: bool = False


@dataclass
class PeriodicConfig:
    enabled: bool = True
    spec: str = ""            # cron expression
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    timezone: str = "UTC"


@dataclass
class ParameterizedJobConfig:
    payload: str = "optional"  # optional|required|forbidden
    meta_required: List[str] = field(default_factory=list)
    meta_optional: List[str] = field(default_factory=list)


def _path_escapes_sandbox(rel: str) -> bool:
    """True when a user-supplied relative path climbs out of its sandbox
    dir (reference: helper/funcs.go PathEscapesAllocDir — normalize then
    check for a leading '..')."""
    import posixpath
    norm = posixpath.normpath("/" + rel.lstrip("/"))
    # After anchoring at '/', normpath collapses every '..'; a path that
    # still tries to climb shows up as a difference vs the raw join.
    raw = posixpath.normpath(posixpath.join("/sandbox", rel.lstrip("/")))
    return not (raw == "/sandbox" or raw.startswith("/sandbox/")) or norm == "/"


@dataclass
class DispatchPayloadConfig:
    file: str = ""


@dataclass
class ServiceCheck:
    name: str = ""
    type: str = ""            # http|tcp|script|grpc
    path: str = ""
    command: str = ""
    args: List[str] = field(default_factory=list)
    interval_s: float = 10.0
    timeout_s: float = 2.0
    port_label: str = ""


@dataclass
class Service:
    name: str = ""
    port_label: str = ""
    tags: List[str] = field(default_factory=list)
    canary_tags: List[str] = field(default_factory=list)
    checks: List[ServiceCheck] = field(default_factory=list)
    address_mode: str = "auto"


@dataclass
class VolumeRequest:
    name: str = ""
    type: str = "host"        # host|csi
    source: str = ""
    read_only: bool = False


@dataclass
class VolumeMount:
    volume: str = ""
    destination: str = ""
    read_only: bool = False


@dataclass
class LogConfig:
    max_files: int = 10
    max_file_size_mb: int = 10


@dataclass
class Template:
    source_path: str = ""
    dest_path: str = ""
    embedded_tmpl: str = ""
    change_mode: str = "restart"
    change_signal: str = ""


@dataclass
class Artifact:
    getter_source: str = ""
    getter_options: Dict[str, str] = field(default_factory=dict)
    relative_dest: str = ""


@dataclass
class Task:
    name: str = ""
    driver: str = ""
    user: str = ""
    config: Dict[str, object] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    services: List[Service] = field(default_factory=list)
    resources: Resources = field(default_factory=Resources)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)
    kill_timeout_s: float = 5.0
    kill_signal: str = ""
    leader: bool = False
    shutdown_delay_s: float = 0.0
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    templates: List[Template] = field(default_factory=list)
    artifacts: List[Artifact] = field(default_factory=list)
    dispatch_payload: Optional[DispatchPayloadConfig] = None
    log_config: LogConfig = field(default_factory=LogConfig)
    lifecycle: Optional[dict] = None


@dataclass
class TaskGroup:
    name: str = ""
    count: int = 1
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    tasks: List[Task] = field(default_factory=list)
    networks: List[NetworkResource] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    reschedule_policy: Optional[ReschedulePolicy] = None
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    update: Optional[UpdateStrategy] = None
    migrate: Optional[MigrateStrategy] = None
    volumes: Dict[str, VolumeRequest] = field(default_factory=dict)
    stop_after_client_disconnect_s: Optional[float] = None
    meta: Dict[str, str] = field(default_factory=dict)

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None


@dataclass
class Job:
    id: str = ""
    name: str = ""
    namespace: str = DEFAULT_NAMESPACE
    region: str = DEFAULT_REGION
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    datacenters: List[str] = field(default_factory=lambda: ["dc1"])
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    task_groups: List[TaskGroup] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    periodic: Optional[PeriodicConfig] = None
    parameterized: Optional[ParameterizedJobConfig] = None
    payload: bytes = b""
    meta: Dict[str, str] = field(default_factory=dict)
    vault_token: str = ""
    status: str = JOB_STATUS_PENDING
    status_description: str = ""
    stable: bool = False
    version: int = 0
    stop: bool = False
    parent_id: str = ""
    dispatched: bool = False
    submit_time: float = 0.0
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0

    # -- helpers used throughout scheduling --
    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def stopped(self) -> bool:
        return self.stop

    def is_periodic(self) -> bool:
        return self.periodic is not None

    def is_parameterized(self) -> bool:
        return self.parameterized is not None and not self.dispatched

    def is_system(self) -> bool:
        return self.type == JOB_TYPE_SYSTEM

    def is_service(self) -> bool:
        return self.type == JOB_TYPE_SERVICE

    def is_batch(self) -> bool:
        return self.type == JOB_TYPE_BATCH

    def has_update_strategy(self) -> bool:
        return any(tg.update is not None and tg.update.rolling()
                   for tg in self.task_groups)

    def canonicalize(self) -> None:
        """Fill defaults (reference: Job.Canonicalize)."""
        if not self.name:
            self.name = self.id
        if not self.namespace:
            self.namespace = DEFAULT_NAMESPACE
        for tg in self.task_groups:
            if tg.count == 0 and self.type != JOB_TYPE_SYSTEM:
                tg.count = 1
            if tg.reschedule_policy is None:
                if self.type == JOB_TYPE_SERVICE:
                    tg.reschedule_policy = ReschedulePolicy.default_service()
                elif self.type == JOB_TYPE_BATCH:
                    tg.reschedule_policy = ReschedulePolicy.default_batch()
            if tg.update is None and self.update is not None:
                tg.update = self.update

    def validate(self) -> List[str]:
        """Minimal structural validation (reference: Job.Validate)."""
        errs = []
        if not self.id:
            errs.append("missing job ID")
        if " " in self.id:
            errs.append("job ID contains a space")
        if not self.task_groups:
            errs.append("missing job task groups")
        if not self.datacenters:
            errs.append("missing job datacenters")
        if self.type not in (JOB_TYPE_SERVICE, JOB_TYPE_BATCH, JOB_TYPE_SYSTEM):
            errs.append(f"invalid job type: {self.type}")
        seen = set()
        for tg in self.task_groups:
            if tg.name in seen:
                errs.append(f"duplicate task group {tg.name}")
            seen.add(tg.name)
            if not tg.tasks:
                errs.append(f"task group {tg.name} has no tasks")
            if self.type == JOB_TYPE_SYSTEM and tg.reschedule_policy is not None:
                errs.append("system jobs do not support reschedule policy")
            tseen = set()
            for t in tg.tasks:
                if t.name in tseen:
                    errs.append(f"duplicate task {t.name} in group {tg.name}")
                tseen.add(t.name)
                if not t.driver:
                    errs.append(f"task {t.name} missing driver")
                dp = getattr(t, "dispatch_payload", None)
                if dp and dp.file and _path_escapes_sandbox(dp.file):
                    errs.append(
                        f"task {t.name} dispatch_payload file "
                        f"{dp.file!r} escapes the task directory")
        if self.type == JOB_TYPE_SYSTEM:
            if self.affinities:
                errs.append("system jobs may not have an affinity stanza")
            if self.spreads:
                errs.append("system jobs may not have a spread stanza")
        return errs

    def required_signals(self) -> Dict[str, Dict[str, List[str]]]:
        return {}

    def combined_task_meta(self, tg_name: str, task_name: str) -> Dict[str, str]:
        out = dict(self.meta)
        tg = self.lookup_task_group(tg_name)
        if tg:
            out.update(tg.meta)
            t = tg.lookup_task(task_name)
            if t:
                out.update(t.meta)
        return out
