"""Allocation: the unit of placed work, plus its scheduling metadata.

Reference: nomad/structs/structs.go `Allocation` :8071, `AllocMetric` :8672,
RescheduleTracker / RescheduleEvent, DesiredTransition.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .consts import (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
                     ALLOC_CLIENT_LOST, ALLOC_CLIENT_PENDING,
                     ALLOC_DESIRED_EVICT, ALLOC_DESIRED_STOP,
                     RESCHEDULE_DELAY_EXPONENTIAL, RESCHEDULE_DELAY_FIBONACCI,
                     TASK_STATE_DEAD)
from .job import Job, ReschedulePolicy
from .resources import AllocatedResources, ComparableResources


@dataclass
class TaskEvent:
    type: str = ""
    time: float = 0.0
    message: str = ""
    details: Dict[str, str] = field(default_factory=dict)
    exit_code: int = 0
    signal: int = 0
    restart_reason: str = ""
    failure: bool = False


@dataclass
class TaskState:
    state: str = "pending"
    failed: bool = False
    restarts: int = 0
    last_restart: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    events: List[TaskEvent] = field(default_factory=list)
    # "<service>/<check>" -> passing (client-side check runner results;
    # reference: consul check status consumed by the service catalog)
    checks: Dict[str, bool] = field(default_factory=dict)

    def successful(self) -> bool:
        return self.state == TASK_STATE_DEAD and not self.failed


@dataclass
class RescheduleEvent:
    reschedule_time: float = 0.0
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_s: float = 0.0


@dataclass
class RescheduleTracker:
    events: List[RescheduleEvent] = field(default_factory=list)

    def copy(self) -> "RescheduleTracker":
        return RescheduleTracker(events=list(self.events))


@dataclass
class DesiredTransition:
    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def should_migrate(self) -> bool:
        return bool(self.migrate)

    def should_force_reschedule(self) -> bool:
        return bool(self.force_reschedule)


@dataclass
class AllocDeploymentStatus:
    healthy: Optional[bool] = None
    timestamp: float = 0.0
    canary: bool = False
    modify_index: int = 0

    def is_healthy(self) -> bool:
        return self.healthy is True

    def is_unhealthy(self) -> bool:
        return self.healthy is False


@dataclass
class AllocMetric:
    """Per-placement explainability (reference: structs.go:8672).

    The TPU solver populates this from its mask/score tensors so `alloc status`
    output matches the reference's debugging surface.
    """
    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)   # per-dc
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    quota_exhausted: List[str] = field(default_factory=list)
    scores: Dict[str, float] = field(default_factory=dict)          # legacy
    score_meta: List[dict] = field(default_factory=list)            # top-K
    allocation_time_ns: int = 0
    coalesced_failures: int = 0

    def exhausted_node(self, node_id: str, node_class: str, dimension: str):
        self.nodes_exhausted += 1
        if node_class:
            self.class_exhausted[node_class] = self.class_exhausted.get(node_class, 0) + 1
        if dimension:
            self.dimension_exhausted[dimension] = self.dimension_exhausted.get(dimension, 0) + 1

    def filter_node(self, node_class: str, constraint: str):
        self.nodes_filtered += 1
        if node_class:
            self.class_filtered[node_class] = self.class_filtered.get(node_class, 0) + 1
        if constraint:
            self.constraint_filtered[constraint] = self.constraint_filtered.get(constraint, 0) + 1

    def copy(self) -> "AllocMetric":
        return AllocMetric(
            nodes_evaluated=self.nodes_evaluated,
            nodes_filtered=self.nodes_filtered,
            nodes_available=dict(self.nodes_available),
            class_filtered=dict(self.class_filtered),
            constraint_filtered=dict(self.constraint_filtered),
            nodes_exhausted=self.nodes_exhausted,
            class_exhausted=dict(self.class_exhausted),
            dimension_exhausted=dict(self.dimension_exhausted),
            quota_exhausted=list(self.quota_exhausted),
            scores=dict(self.scores),
            score_meta=[dict(m) for m in self.score_meta],
            allocation_time_ns=self.allocation_time_ns,
            coalesced_failures=self.coalesced_failures,
        )


@dataclass
class Allocation:
    id: str = ""
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""                 # "<job>.<group>[<index>]"
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None      # job snapshot at placement time
    task_group: str = ""
    allocated_resources: AllocatedResources = field(default_factory=AllocatedResources)
    metrics: AllocMetric = field(default_factory=AllocMetric)
    desired_status: str = "run"
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = ALLOC_CLIENT_PENDING
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[AllocDeploymentStatus] = None
    reschedule_tracker: Optional[RescheduleTracker] = None
    previous_allocation: str = ""
    next_allocation: str = ""
    follow_up_eval_id: str = ""
    preempted_by_allocation: str = ""
    preempted_allocations: List[str] = field(default_factory=list)
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0
    create_time: float = 0.0
    modify_time: float = 0.0

    # -- status predicates (reference: Allocation.TerminalStatus etc.) --
    def terminal_status(self) -> bool:
        """Desired or actual status implies no more resource usage."""
        if self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            return True
        return self.client_terminal_status()

    def client_terminal_status(self) -> bool:
        return self.client_status in (ALLOC_CLIENT_COMPLETE,
                                      ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST)

    def server_terminal_status(self) -> bool:
        return self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT)

    def ran_successfully(self) -> bool:
        if not self.task_states:
            return False
        return all(ts.successful() for ts in self.task_states.values())

    def comparable_resources(self) -> ComparableResources:
        return self.allocated_resources.comparable()

    def index(self) -> int:
        """Parse the name index: "job.group[3]" -> 3."""
        l = self.name.rfind("[")
        r = self.name.rfind("]")
        if l < 0 or r < 0 or r <= l:
            return -1
        try:
            return int(self.name[l + 1:r])
        except ValueError:
            return -1

    def job_namespaced_id(self):
        return (self.namespace, self.job_id)

    # -- rescheduling (reference: Allocation.ShouldReschedule / NextRescheduleTime) --
    def should_reschedule(self, policy: Optional[ReschedulePolicy],
                          fail_time: float) -> bool:
        if self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            return False
        if self.client_status != ALLOC_CLIENT_FAILED:
            return False
        return self.reschedule_eligible(policy, fail_time)

    def reschedule_eligible(self, policy: Optional[ReschedulePolicy],
                            fail_time: float) -> bool:
        """Reference: Allocation.RescheduleEligible."""
        if policy is None:
            return False
        if not (policy.attempts > 0 or policy.unlimited):
            return False
        if policy.unlimited:
            return True
        attempted = self.reschedule_attempts_in_interval(policy, fail_time)
        return attempted < policy.attempts

    def reschedule_attempts_in_interval(self, policy: ReschedulePolicy,
                                        fail_time: float) -> int:
        if not self.reschedule_tracker:
            return 0
        window = fail_time - policy.interval_s
        return sum(1 for ev in self.reschedule_tracker.events
                   if ev.reschedule_time > window)

    def next_delay(self, policy: ReschedulePolicy) -> float:
        """Compute the reschedule delay from the recorded event history
        (reference: Allocation.NextDelay — exponential doubles the last
        recorded delay; fibonacci sums the last two, with a ceiling reset
        once two consecutive events sat at max_delay; hitting the clamp
        after a quiet period longer than the delay resets to base)."""
        base = policy.delay_s
        events = self.reschedule_tracker.events if self.reschedule_tracker else []
        if not events:
            return base
        fn = policy.delay_function
        if fn == RESCHEDULE_DELAY_EXPONENTIAL:
            delay = events[-1].delay_s * 2
        elif fn == RESCHEDULE_DELAY_FIBONACCI:
            if len(events) >= 2:
                d1, d2 = events[-1].delay_s, events[-2].delay_s
                # ceiling reset: series restarted at base after hitting max
                if d2 == policy.max_delay_s and d1 == policy.delay_s:
                    delay = d1
                else:
                    delay = d1 + d2
            else:
                delay = base
        else:
            return base
        if policy.max_delay_s > 0 and delay > policy.max_delay_s:
            delay = policy.max_delay_s
            if self.last_event_time() - events[-1].reschedule_time > delay:
                delay = policy.delay_s
        return delay

    def next_reschedule_time(self, policy: Optional[ReschedulePolicy]):
        """Returns (eligible_time, eligible) for a delayed reschedule
        (reference: Allocation.NextRescheduleTime)."""
        if (policy is None
                or self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT)
                or self.client_status != ALLOC_CLIENT_FAILED):
            return 0.0, False
        fail_time = self.last_event_time()
        if fail_time <= 0:
            return 0.0, False
        next_delay = self.next_delay(policy)
        eligible = policy.unlimited or (policy.attempts > 0
                                        and self.reschedule_tracker is None)
        if (policy.attempts > 0 and self.reschedule_tracker
                and self.reschedule_tracker.events):
            attempted = self.reschedule_attempts_in_interval(policy, fail_time)
            eligible = (attempted < policy.attempts
                        and next_delay < policy.interval_s)
        return fail_time + next_delay, eligible

    def last_event_time(self) -> float:
        last = 0.0
        for ts in self.task_states.values():
            if ts.finished_at > last:
                last = ts.finished_at
        return last or self.modify_time

    def migrate_disk(self) -> bool:
        if self.job is None:
            return False
        tg = self.job.lookup_task_group(self.task_group)
        return bool(tg and tg.ephemeral_disk.migrate)

    def stub(self) -> dict:
        return {
            "ID": self.id, "EvalID": self.eval_id, "Name": self.name,
            "NodeID": self.node_id, "JobID": self.job_id,
            "TaskGroup": self.task_group,
            "DesiredStatus": self.desired_status,
            "ClientStatus": self.client_status,
            "DeploymentID": self.deployment_id,
            "FollowupEvalID": self.follow_up_eval_id,
            "CreateIndex": self.create_index, "ModifyIndex": self.modify_index,
        }


def alloc_name(job_id: str, group: str, idx: int) -> str:
    return f"{job_id}.{group}[{idx}]"
