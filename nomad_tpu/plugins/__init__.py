"""Plugin system: the process/ABI boundary between the client core and
task drivers / device plugins.

Reference: plugins/base (PluginInfo/ConfigSchema/SetConfig),
plugins/drivers/driver.go:40-58 (DriverPlugin), plugins/device
(Fingerprint/Reserve/Stats). The reference runs external plugins as
go-plugin gRPC subprocesses and builtins in-process
(helper/pluginutils/catalog/register.go:15-19); here builtins are
in-process Python classes behind the same interface, and the
subprocess boundary lives one level lower — in the per-task executor
(nomad_tpu/drivers/executor.py) that outlives the agent.
"""
from .base import PluginInfo
from .drivers import (DriverCapabilities, DriverFingerprint, DriverPlugin,
                      DriverRegistry, ExitResult, TaskConfig, TaskHandle,
                      TaskStatus, default_registry)

__all__ = [
    "PluginInfo", "DriverPlugin", "DriverCapabilities", "DriverFingerprint",
    "DriverRegistry", "ExitResult", "TaskConfig", "TaskHandle", "TaskStatus",
    "default_registry",
]
