"""Base plugin protocol (reference: plugins/base/base.go).

Every plugin — driver or device — reports identity/version via
PluginInfo and accepts a config dict validated against its declared
schema keys (the hclspec analog: a flat {key: (type, default)} table
rather than a full HCL schema compiler).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

PLUGIN_TYPE_DRIVER = "driver"
PLUGIN_TYPE_DEVICE = "device"

API_VERSION = "v0.1.0"


@dataclass
class PluginInfo:
    name: str = ""
    type: str = PLUGIN_TYPE_DRIVER
    plugin_api_versions: Tuple[str, ...] = (API_VERSION,)
    plugin_version: str = "0.1.0"


class BasePlugin:
    """In-process plugin contract (reference: base.BasePlugin)."""

    #: config schema: key -> (python type, default). Unknown keys are a
    #: validation error, mirroring the reference's strict hclspec decode.
    config_schema: Dict[str, Tuple[type, Any]] = {}

    def plugin_info(self) -> PluginInfo:
        raise NotImplementedError

    def set_config(self, config: Dict[str, Any]) -> None:
        self._config = self.validate_config(config)

    @classmethod
    def validate_config(cls, config: Dict[str, Any]) -> Dict[str, Any]:
        out = {k: default for k, (_, default) in cls.config_schema.items()}
        for key, value in (config or {}).items():
            if key not in cls.config_schema:
                raise ValueError(f"unknown plugin config key {key!r}")
            want, _ = cls.config_schema[key]
            if want is float and isinstance(value, int):
                value = float(value)
            if not isinstance(value, want):
                raise ValueError(
                    f"plugin config {key!r}: want {want.__name__}, "
                    f"got {type(value).__name__}")
            out[key] = value
        return out
