"""Device plugin interface — the TPU-relevant plugin class.

Reference: plugins/device/ — the gRPC protocol every device plugin
speaks: `Fingerprint` streams the device inventory (groups of
instances with attributes), `Reserve` returns the container access
recipe (env vars, mounts) for specific instance ids, `Stats` reports
per-instance usage. devices/gpu/nvidia is the built-in blueprint; the
TPU build's first-party plugin introspects the JAX runtime instead of
NVML.

In-process plugins here follow the same registry pattern as the task
drivers (plugins/drivers.py); the wire protocol for OUT-of-process
plugins is the rpc package's framed JSON, not gRPC.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..structs import NodeDevice, NodeDeviceResource

_log = logging.getLogger(__name__)


@dataclass
class ContainerReservation:
    """How a task gets access to reserved instances (reference:
    plugins/device/device.go ContainerReservation)."""
    envs: Dict[str, str] = field(default_factory=dict)
    mounts: List[Dict[str, str]] = field(default_factory=list)
    devices: List[str] = field(default_factory=list)


class DevicePlugin:
    """Base protocol (reference: plugins/device/device.go:31-44)."""

    name = "device"

    def fingerprint(self) -> List[NodeDeviceResource]:
        """The device inventory this node offers."""
        raise NotImplementedError

    def reserve(self, device_ids: List[str]) -> ContainerReservation:
        """Access recipe for specific instance ids at task start."""
        raise NotImplementedError

    def stats(self) -> Dict[str, Dict[str, float]]:
        """instance id -> stats gauges."""
        return {}


class TPUDevicePlugin(DevicePlugin):
    """First-party TPU inventory via the JAX runtime (the nvidia/NVML
    analog, devices/gpu/nvidia/device.go). Fingerprinting is fully
    failure-tolerant: hosts without a TPU (or without jax importable in
    the agent's environment) simply offer no devices."""

    name = "tpu"

    def fingerprint(self) -> List[NodeDeviceResource]:
        try:
            import jax
            devices = [d for d in jax.devices()
                       if "tpu" in d.platform.lower()
                       or "TPU" in getattr(d, "device_kind", "")]
        except Exception:                   # noqa: BLE001
            return []
        if not devices:
            return []
        by_kind: Dict[str, List] = {}
        for d in devices:
            by_kind.setdefault(
                getattr(d, "device_kind", "tpu") or "tpu", []).append(d)
        out = []
        for kind, devs in sorted(by_kind.items()):
            out.append(NodeDeviceResource(
                vendor="google", type="tpu", name=kind,
                instances=[NodeDevice(id=f"tpu-{d.id}", healthy=True)
                           for d in devs],
                attributes={"device_kind": kind,
                            "count": len(devs)}))
        return out

    def reserve(self, device_ids: List[str]) -> ContainerReservation:
        ordinals = ",".join(i.rsplit("-", 1)[-1] for i in device_ids)
        return ContainerReservation(
            envs={"TPU_VISIBLE_DEVICES": ordinals,
                  "NOMAD_DEVICE_TPU": ",".join(device_ids)},
            devices=list(device_ids))


class MockDevicePlugin(DevicePlugin):
    """Scriptable inventory for tests (the drivers/mock analog)."""

    name = "mock_device"

    def __init__(self, groups: Optional[List[NodeDeviceResource]] = None,
                 env_key: str = "MOCK_DEVICES"):
        self.groups = groups or []
        self.env_key = env_key
        self.reserved: List[List[str]] = []

    def fingerprint(self) -> List[NodeDeviceResource]:
        return list(self.groups)

    def reserve(self, device_ids: List[str]) -> ContainerReservation:
        self.reserved.append(list(device_ids))
        return ContainerReservation(
            envs={self.env_key: ",".join(device_ids)},
            devices=list(device_ids))


class DevicePluginRegistry:
    """vendor/type/name pattern -> owning plugin (reference:
    client/devicemanager routing by DeviceIdTuple)."""

    def __init__(self, plugins: Optional[List[DevicePlugin]] = None):
        self.plugins = list(plugins or [])
        self._owner: Dict[tuple, DevicePlugin] = {}

    def fingerprint_all(self) -> List[NodeDeviceResource]:
        out = []
        for plugin in self.plugins:
            try:
                groups = plugin.fingerprint()
            except Exception:               # noqa: BLE001
                _log.exception("device plugin %s fingerprint failed",
                               plugin.name)
                continue
            for g in groups:
                self._owner[g.id_tuple()] = plugin
                out.append(g)
        return out

    def reserve(self, vendor: str, typ: str, model: str,
                device_ids: List[str]) -> Optional[ContainerReservation]:
        plugin = self._owner.get((vendor, typ, model))
        if plugin is None:
            return None
        return plugin.reserve(device_ids)


def default_device_registry() -> DevicePluginRegistry:
    return DevicePluginRegistry([TPUDevicePlugin()])
