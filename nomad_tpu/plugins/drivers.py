"""Driver plugin interface (reference: plugins/drivers/driver.go:40-58).

The contract the client's task runner drives:
  fingerprint / start_task / wait_task / stop_task / destroy_task /
  recover_task / inspect_task / signal_task / exec_task.

TaskHandle is the serializable re-attach token (reference:
plugins/drivers/task_handle.go): persisted in the client state DB so a
restarted agent can RecoverTask instead of re-running the workload.
"""
from __future__ import annotations

import os
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .base import BasePlugin, PluginInfo

TASK_STATE_RUNNING = "running"
TASK_STATE_EXITED = "exited"
TASK_STATE_UNKNOWN = "unknown"

HEALTH_UNDETECTED = "undetected"
HEALTH_HEALTHY = "healthy"


@dataclass
class DriverCapabilities:
    """reference: drivers.Capabilities."""
    send_signals: bool = True
    exec: bool = False
    fs_isolation: str = "none"       # none|chroot|image


@dataclass
class DriverFingerprint:
    """reference: drivers.Fingerprint (plugins/drivers/driver.go:214)."""
    attributes: Dict[str, str] = field(default_factory=dict)
    health: str = HEALTH_HEALTHY
    health_description: str = ""


@dataclass
class TaskConfig:
    """What the task runner hands the driver (reference: drivers.TaskConfig).

    `id` is the driver-scoped task id (alloc id + task name), `config` the
    task's jobspec driver config block, and the dir/log paths come from the
    allocdir layout so the driver never invents paths.
    """
    id: str = ""
    name: str = ""
    alloc_id: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    config: Dict[str, Any] = field(default_factory=dict)
    user: str = ""
    cpu_mhz: int = 0
    memory_mb: int = 0
    task_dir: str = ""
    alloc_dir: str = ""
    stdout_path: str = ""
    stderr_path: str = ""
    # size-rotated logging (reference: LogConfig -> logmon rotation);
    # 0 disables rotation
    log_max_files: int = 10
    log_max_file_size_mb: int = 10


@dataclass
class TaskHandle:
    """Serializable re-attach token (reference: task_handle.go)."""
    driver: str = ""
    task_id: str = ""
    version: int = 1
    config: Optional[TaskConfig] = None
    state: str = TASK_STATE_RUNNING
    driver_state: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ExitResult:
    """reference: drivers.ExitResult."""
    exit_code: int = 0
    signal: int = 0
    oom_killed: bool = False
    err: str = ""

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.err


@dataclass
class TaskStatus:
    """reference: drivers.TaskStatus."""
    id: str = ""
    name: str = ""
    state: str = TASK_STATE_UNKNOWN
    started_at: float = 0.0
    completed_at: float = 0.0
    exit_result: Optional[ExitResult] = None
    driver_attributes: Dict[str, str] = field(default_factory=dict)


class DriverError(Exception):
    pass


class TaskNotFoundError(DriverError):
    pass


class DriverPlugin(BasePlugin):
    """The driver contract (reference: plugins/drivers/driver.go:40-58)."""

    name = "?"
    capabilities = DriverCapabilities()

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name=self.name, type="driver")

    def fingerprint(self) -> DriverFingerprint:
        raise NotImplementedError

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        raise NotImplementedError

    def wait_task(self, task_id: str,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        """Block until the task exits; None on timeout."""
        raise NotImplementedError

    def stop_task(self, task_id: str, timeout_s: float,
                  signal: str = "") -> None:
        raise NotImplementedError

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        raise NotImplementedError

    def recover_task(self, handle: TaskHandle) -> None:
        """Re-attach to a task from a persisted handle; raises
        TaskNotFoundError if it cannot be recovered."""
        raise NotImplementedError

    def inspect_task(self, task_id: str) -> TaskStatus:
        raise NotImplementedError

    def signal_task(self, task_id: str, signal: str) -> None:
        raise DriverError(f"driver {self.name} does not support signals")

    def exec_task(self, task_id: str, cmd: List[str],
                  timeout_s: float = 30.0) -> Tuple[bytes, int]:
        raise DriverError(f"driver {self.name} does not support exec")

    def exec_task_streaming(self, task_id: str, cmd: List[str],
                            tty: bool = True, width: int = 80,
                            height: int = 24) -> "ExecStream":
        """Interactive exec in the task's context (reference:
        plugins/drivers/execstreaming.go ExecTaskStreaming — the bidi
        form behind `alloc exec -i -t`)."""
        raise DriverError(
            f"driver {self.name} does not support streaming exec")


class ExecStream:
    """A live interactive exec session handle.

    `fd` is a bidirectional file descriptor (the pty master for
    tty=True, a socketpair end otherwise): read it for the command's
    output, write to it for stdin.  The bridge layer (HTTP websocket)
    pumps it; the driver owns process lifetime.
    """

    def __init__(self, fd: int, pid: int, tty: bool, popen=None):
        self.fd = fd
        self.pid = pid
        self.tty = tty
        self._popen = popen       # reaps the child when provided
        self._exit_code: Optional[int] = None

    def resize(self, width: int, height: int) -> None:
        if not self.tty:
            return
        import fcntl
        import struct as _struct
        import termios
        try:
            fcntl.ioctl(self.fd, termios.TIOCSWINSZ,
                        _struct.pack("HHHH", height, width, 0, 0))
        except OSError:
            pass

    def close_stdin(self) -> None:
        """Half-close for pipe mode; a no-op for ttys (EOF is ^D)."""
        if self.tty:
            return
        import socket as _socket
        try:
            _socket.socket(fileno=os.dup(self.fd)).shutdown(
                _socket.SHUT_WR)
        except OSError:
            pass

    def poll(self) -> Optional[int]:
        """Exit code if the process has finished, else None."""
        if self._exit_code is not None:
            return self._exit_code
        if self._popen is not None:
            rc = self._popen.poll()
            if rc is None:
                return None
            self._exit_code = 128 - rc if rc < 0 else rc
            return self._exit_code
        try:
            pid, status = os.waitpid(self.pid, os.WNOHANG)
        except ChildProcessError:
            self._exit_code = -1
            return self._exit_code
        if pid == 0:
            return None
        if os.WIFEXITED(status):
            self._exit_code = os.WEXITSTATUS(status)
        elif os.WIFSIGNALED(status):
            self._exit_code = 128 + os.WTERMSIG(status)
        else:
            self._exit_code = -1
        return self._exit_code

    def terminate(self) -> None:
        try:
            os.kill(self.pid, 15)
        except OSError:
            pass

    def close(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass


class DriverRegistry:
    """Builtin driver catalog (reference:
    helper/pluginutils/catalog/register.go:15-19 + the client's
    pluginmanager/drivermanager). Owns one plugin instance per driver
    name and aggregates their fingerprints for the node."""

    def __init__(self):
        self._drivers: Dict[str, DriverPlugin] = {}
        self._lock = threading.Lock()

    def register(self, driver: DriverPlugin) -> None:
        with self._lock:
            self._drivers[driver.name] = driver

    def get(self, name: str) -> Optional[DriverPlugin]:
        with self._lock:
            return self._drivers.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._drivers)

    def fingerprints(self) -> Dict[str, DriverFingerprint]:
        with self._lock:
            drivers = dict(self._drivers)
        out = {}
        for name, drv in drivers.items():
            try:
                out[name] = drv.fingerprint()
            except Exception as e:
                out[name] = DriverFingerprint(
                    health="unhealthy", health_description=str(e))
        return out


def default_registry() -> DriverRegistry:
    """Registry with the builtin drivers registered."""
    from ..drivers import register_builtins
    reg = DriverRegistry()
    register_builtins(reg)
    return reg
