"""CSI plugin protocol: external storage plugins over the framed RPC.

Reference: plugins/csi/ — the reference speaks gRPC CSI
(csi.v1.Controller / csi.v1.Node, plugins/csi/client.go) to
out-of-process storage drivers, with a fake in-tree implementation for
tests (plugins/csi/fake).  This build carries the same protocol shape
over its own wire transport (nomad_tpu/rpc/wire.py framed TCP — the
transport every other boundary here uses), keeping the verb surface and
semantics aligned with the CSI spec the reference consumes:

  controller:  create_volume / delete_volume / publish_volume /
               unpublish_volume / validate_capabilities
  node:        stage_volume / publish_volume / unstage_volume /
               unpublish_volume / get_info
  identity:    probe / plugin_info

`CSIPluginServer` is the base an external plugin implements (run it in
any process; register its address with the client's CSIManager), and
`CSIPluginClient` is the typed caller used by the server's volume
endpoints and the client's mount lifecycle.  `HostPathPlugin` is the
first-party reference plugin (volumes = host directories, publish =
bind mount with symlink fallback) standing in for plugins/csi/fake.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..rpc.client import RpcClient, RpcError
from ..rpc.server import RpcHandlerError, RpcServer


class CSIError(Exception):
    pass


# ---------------------------------------------------------------- server
class CSIPluginServer:
    """Base class for an external CSI-style plugin process.

    Subclasses override the controller_*/node_* methods they support
    and declare capabilities; unimplemented verbs return typed errors
    (the CSI spec's UNIMPLEMENTED)."""

    name = "csi-plugin"
    #: which services this plugin provides
    controller = True
    node = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._rpc = RpcServer(host, port)
        for verb, fn in self._verbs().items():
            self._rpc.register(verb, fn)

    @property
    def addr(self) -> Tuple[str, int]:
        return self._rpc.addr

    def start(self) -> None:
        self._rpc.start()

    def stop(self) -> None:
        self._rpc.stop()

    def _verbs(self) -> Dict[str, Any]:
        def wrap(fn):
            def handler(params: List[Any]):
                try:
                    return fn(**(params[0] if params else {}))
                except CSIError as e:
                    raise RpcHandlerError("csi", str(e))
            return handler

        return {
            "csi.probe": wrap(self.probe),
            "csi.plugin_info": wrap(self.plugin_info),
            "csi.controller.create_volume":
                wrap(self.controller_create_volume),
            "csi.controller.delete_volume":
                wrap(self.controller_delete_volume),
            "csi.controller.publish_volume":
                wrap(self.controller_publish_volume),
            "csi.controller.unpublish_volume":
                wrap(self.controller_unpublish_volume),
            "csi.controller.validate_capabilities":
                wrap(self.controller_validate),
            "csi.node.stage_volume": wrap(self.node_stage_volume),
            "csi.node.publish_volume": wrap(self.node_publish_volume),
            "csi.node.unstage_volume": wrap(self.node_unstage_volume),
            "csi.node.unpublish_volume":
                wrap(self.node_unpublish_volume),
            "csi.node.get_info": wrap(self.node_get_info),
        }

    # ------------------------------------------------------- identity
    def probe(self) -> Dict[str, Any]:
        return {"ready": True}

    def plugin_info(self) -> Dict[str, Any]:
        return {"name": self.name, "version": "0.1.0",
                "controller": self.controller, "node": self.node}

    # ----------------------------------------------------- controller
    def controller_create_volume(self, **kw) -> Dict[str, Any]:
        raise CSIError("unimplemented: create_volume")

    def controller_delete_volume(self, **kw) -> Dict[str, Any]:
        raise CSIError("unimplemented: delete_volume")

    def controller_publish_volume(self, **kw) -> Dict[str, Any]:
        raise CSIError("unimplemented: controller_publish_volume")

    def controller_unpublish_volume(self, **kw) -> Dict[str, Any]:
        raise CSIError("unimplemented: controller_unpublish_volume")

    def controller_validate(self, **kw) -> Dict[str, Any]:
        return {"confirmed": True}

    # ----------------------------------------------------------- node
    def node_stage_volume(self, **kw) -> Dict[str, Any]:
        raise CSIError("unimplemented: node_stage_volume")

    def node_publish_volume(self, **kw) -> Dict[str, Any]:
        raise CSIError("unimplemented: node_publish_volume")

    def node_unstage_volume(self, **kw) -> Dict[str, Any]:
        raise CSIError("unimplemented: node_unstage_volume")

    def node_unpublish_volume(self, **kw) -> Dict[str, Any]:
        raise CSIError("unimplemented: node_unpublish_volume")

    def node_get_info(self) -> Dict[str, Any]:
        return {"node_id": self.name, "max_volumes": 0}


# ---------------------------------------------------------------- client
class CSIPluginClient:
    """Typed caller mirroring plugins/csi/client.go's method surface."""

    def __init__(self, addr: Tuple[str, int]):
        self._c = RpcClient(addr)

    def _call(self, verb: str, **kw):
        try:
            return self._c.call(verb, [kw])
        except RpcError as e:
            raise CSIError(e.message or str(e)) from e
        except ConnectionError as e:
            raise CSIError(f"plugin unreachable: {e}") from e

    def probe(self) -> bool:
        return bool(self._call("csi.probe").get("ready"))

    def plugin_info(self) -> Dict[str, Any]:
        return self._call("csi.plugin_info")

    def create_volume(self, volume_id: str, capacity_bytes: int = 0,
                      params: Optional[Dict] = None) -> Dict[str, Any]:
        return self._call("csi.controller.create_volume",
                          volume_id=volume_id,
                          capacity_bytes=capacity_bytes,
                          params=params or {})

    def delete_volume(self, volume_id: str) -> Dict[str, Any]:
        return self._call("csi.controller.delete_volume",
                          volume_id=volume_id)

    def controller_publish(self, volume_id: str,
                           node_id: str) -> Dict[str, Any]:
        return self._call("csi.controller.publish_volume",
                          volume_id=volume_id, node_id=node_id)

    def controller_unpublish(self, volume_id: str,
                             node_id: str) -> Dict[str, Any]:
        return self._call("csi.controller.unpublish_volume",
                          volume_id=volume_id, node_id=node_id)

    def validate(self, volume_id: str, mode: str) -> bool:
        return bool(self._call("csi.controller.validate_capabilities",
                               volume_id=volume_id,
                               mode=mode).get("confirmed"))

    def node_stage(self, volume_id: str, staging_path: str,
                   publish_context: Optional[Dict] = None) -> None:
        self._call("csi.node.stage_volume", volume_id=volume_id,
                   staging_path=staging_path,
                   publish_context=publish_context or {})

    def node_publish(self, volume_id: str, staging_path: str,
                     target_path: str, read_only: bool = False) -> None:
        self._call("csi.node.publish_volume", volume_id=volume_id,
                   staging_path=staging_path, target_path=target_path,
                   read_only=read_only)

    def node_unstage(self, volume_id: str, staging_path: str) -> None:
        self._call("csi.node.unstage_volume", volume_id=volume_id,
                   staging_path=staging_path)

    def node_unpublish(self, volume_id: str, target_path: str) -> None:
        self._call("csi.node.unpublish_volume", volume_id=volume_id,
                   target_path=target_path)

    def node_info(self) -> Dict[str, Any]:
        return self._call("csi.node.get_info")


# --------------------------------------------------------- hostpath impl
def _try_bind_mount(src: str, dst: str, read_only: bool) -> bool:
    try:
        from ..drivers.isolation import (MS_BIND, MS_RDONLY, MS_REMOUNT,
                                         _mount)
        _mount(src, dst, None, MS_BIND)
        if read_only:
            _mount(None, dst, None, MS_REMOUNT | MS_BIND | MS_RDONLY)
        return True
    except OSError:
        return False


def _try_unmount(path: str) -> bool:
    import ctypes
    import ctypes.util
    libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                       use_errno=True)
    return libc.umount2(path.encode(), 0) == 0


class HostPathPlugin(CSIPluginServer):
    """First-party hostpath CSI plugin (reference: plugins/csi/fake +
    the canonical hostpath CSI driver).  Volumes are directories under
    `root`; staging verifies/creates them; publish bind-mounts the
    volume at the target (symlink fallback for unprivileged hosts)."""

    name = "hostpath"

    def __init__(self, root: str, node_id: str = "hostpath-node",
                 host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port)
        self.root = root
        self.node_id = node_id
        self._attached: Dict[str, str] = {}       # vol -> node
        self._published: Dict[str, bool] = {}     # target -> via_mount
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)

    def _vol_dir(self, volume_id: str) -> str:
        safe = volume_id.replace("/", "_")
        return os.path.join(self.root, safe)

    # ----------------------------------------------------- controller
    def controller_create_volume(self, volume_id: str = "",
                                 capacity_bytes: int = 0,
                                 params: Optional[Dict] = None):
        os.makedirs(self._vol_dir(volume_id), exist_ok=True)
        return {"volume_id": volume_id,
                "capacity_bytes": capacity_bytes}

    def controller_delete_volume(self, volume_id: str = ""):
        d = self._vol_dir(volume_id)
        if os.path.isdir(d) and not os.listdir(d):
            os.rmdir(d)
        return {}

    def controller_publish_volume(self, volume_id: str = "",
                                  node_id: str = ""):
        if not os.path.isdir(self._vol_dir(volume_id)):
            raise CSIError(f"unknown volume {volume_id!r}")
        with self._lock:
            self._attached[volume_id] = node_id
        return {"publish_context": {"attached_node": node_id}}

    def controller_unpublish_volume(self, volume_id: str = "",
                                    node_id: str = ""):
        with self._lock:
            self._attached.pop(volume_id, None)
        return {}

    # ----------------------------------------------------------- node
    def node_stage_volume(self, volume_id: str = "",
                          staging_path: str = "",
                          publish_context: Optional[Dict] = None):
        if not os.path.isdir(self._vol_dir(volume_id)):
            raise CSIError(f"unknown volume {volume_id!r}")
        os.makedirs(staging_path, exist_ok=True)
        return {}

    def node_publish_volume(self, volume_id: str = "",
                            staging_path: str = "",
                            target_path: str = "",
                            read_only: bool = False):
        src = self._vol_dir(volume_id)
        if not os.path.isdir(src):
            raise CSIError(f"unknown volume {volume_id!r}")
        os.makedirs(os.path.dirname(target_path), exist_ok=True)
        os.makedirs(target_path, exist_ok=True)
        if _try_bind_mount(src, target_path, read_only):
            with self._lock:
                self._published[target_path] = True
        else:
            os.rmdir(target_path)
            os.symlink(src, target_path)
            with self._lock:
                self._published[target_path] = False
        return {}

    def node_unpublish_volume(self, volume_id: str = "",
                              target_path: str = ""):
        with self._lock:
            via_mount = self._published.pop(target_path, None)
        if via_mount:
            _try_unmount(target_path)
            try:
                os.rmdir(target_path)
            except OSError:
                pass
        elif os.path.islink(target_path):
            os.unlink(target_path)
        return {}

    def node_unstage_volume(self, volume_id: str = "",
                            staging_path: str = ""):
        try:
            os.rmdir(staging_path)
        except OSError:
            pass
        return {}

    def node_get_info(self):
        return {"node_id": self.node_id, "max_volumes": 0}


def _main() -> int:
    """Run the hostpath plugin as a standalone external process:
        python -m nomad_tpu.plugins.csi --root /srv/volumes --port 7070
    """
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="nomad-tpu-csi-hostpath")
    ap.add_argument("--root", required=True,
                    help="directory holding the volume dirs")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--node-id", default="hostpath-node")
    args = ap.parse_args()
    plugin = HostPathPlugin(root=args.root, node_id=args.node_id,
                            host=args.host, port=args.port)
    plugin.start()
    print(f"csi hostpath plugin listening on "
          f"{plugin.addr[0]}:{plugin.addr[1]} root={args.root}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        plugin.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
