"""Preemption: evict lower-priority allocs to make room.

Reference semantics: scheduler/preemption.go — Preemptor :96,
PreemptForTaskGroup :198, resource-distance scoring
`basicResourceDistance` :608, priority grouping with delta >= 10
`filterAndGroupPreemptibleAllocs` :663, redundant-victim filtering :702.

Host-side second pass: the device solve surfaces which placements
exhausted resources on otherwise-feasible nodes; this module picks the
minimum-distance victim set per candidate node.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..structs import Allocation, ComparableResources, Node

PRIORITY_DELTA = 10


def resource_distance(delta_cpu: float, delta_mem: float, delta_disk: float,
                      delta_net: float) -> float:
    """Normalized euclidean distance between a victim's resources and the
    still-needed resources (reference: basicResourceDistance :608)."""
    return (delta_cpu ** 2 + delta_mem ** 2 + delta_disk ** 2
            + delta_net ** 2) ** 0.5


def victim_distance(shortfall: Tuple[float, float, float, float],
                    usage: Tuple[float, float, float, float]) -> float:
    """Distance between a victim's usage and the remaining shortfall,
    each dimension normalized by the shortfall (floored at 1).

    This is THE single victim-cost contract (ISSUE 7): every host pass
    scores candidates through it, and the device eviction pass
    (solver/kernel.py preemption waves) mirrors it float-op-for-float-op
    — a down-payment on ROADMAP item 5's one-scoring-spec refactor.
    Term order inside resource_distance is part of the contract."""
    sc, sm, sd, sn = shortfall
    c, m, d, nw = usage
    return resource_distance((sc - c) / max(sc, 1.0),
                             (sm - m) / max(sm, 1.0),
                             (sd - d) / max(sd, 1.0),
                             (sn - nw) / max(sn, 1.0))


def take_from_groups(job_priority: int, allocs: Sequence[Allocation],
                     met, charge, order_key=None
                     ) -> Tuple[List[Allocation], bool]:
    """Shared victim-accumulation walk: priority groups lowest first
    (group_preemptible), victims inside a group consumed in `order_key`
    order (stable sort; None keeps candidate order), `charge`-ing each
    pick until `met()` — the one loop behind preempt_for_network and
    preempt_for_device (pick_victims re-sorts against a MOVING shortfall
    every pick, so it keeps its own loop over the same cost helper)."""
    victims: List[Allocation] = []
    for grp in group_preemptible(job_priority, allocs):
        if order_key is not None:
            grp.sort(key=order_key)
        for a in grp:
            charge(a)
            victims.append(a)
            if met():
                return victims, True
    return victims, False


def prune_superset(victims: List[Allocation], covers_without, order_key,
                   protected: frozenset = frozenset()
                   ) -> List[Allocation]:
    """Shared redundancy filter (reference :702): walk victims in
    `order_key` order and drop any whose eviction is redundant once the
    rest are out (`covers_without(trial)`), keeping `protected` ids."""
    pruned = list(victims)
    for a in sorted(victims, key=order_key):
        if a.id in protected:
            continue
        trial = [v for v in pruned if v.id != a.id]
        if covers_without(trial):
            pruned = trial
    return pruned


def _usage(alloc: Allocation) -> Tuple[float, float, float, float]:
    c = alloc.comparable_resources()
    return (float(c.cpu), float(c.memory_mb), float(c.disk_mb),
            float(sum(n.mbits for n in c.networks)))


def preemptible_allocs(job_priority: int, allocs: Sequence[Allocation]
                       ) -> List[Allocation]:
    """Victim candidates: non-terminal allocs at least PRIORITY_DELTA
    lower priority, lowest priority first."""
    out = []
    for a in allocs:
        if a.terminal_status():
            continue
        if a.job is None:
            # placeholder/probe allocs without a job snapshot have no
            # knowable priority — never victims
            continue
        prio = a.job.priority
        if job_priority - prio >= PRIORITY_DELTA:
            out.append((prio, a))
    out.sort(key=lambda t: (t[0], t[1].create_index))
    return [a for _p, a in out]


def pick_victims(node: Node, proposed: Sequence[Allocation],
                 job_priority: int, need_cpu: float, need_mem: float,
                 need_disk: float, need_net: float
                 ) -> Optional[List[Allocation]]:
    """Greedy minimum-distance victim selection on one node: repeatedly
    take the candidate closest to the remaining shortfall until the ask
    fits, then drop victims made redundant by later picks (reference:
    PreemptForTaskGroup :198 + :702)."""
    res = node.comparable_resources()
    reserved = node.comparable_reserved_resources()
    used_cpu = float(reserved.cpu)
    used_mem = float(reserved.memory_mb)
    used_disk = float(reserved.disk_mb)
    used_net = 0.0
    for a in proposed:
        c, m, d, nw = _usage(a)
        used_cpu += c
        used_mem += m
        used_disk += d
        used_net += nw
    cap_cpu = float(res.cpu)
    cap_mem = float(res.memory_mb)
    cap_disk = float(res.disk_mb)
    cap_net = float(sum(n.mbits for n in res.networks))

    def shortfall(freed):
        fc, fm, fd, fn = freed
        return (max(0.0, used_cpu - fc + need_cpu - cap_cpu),
                max(0.0, used_mem - fm + need_mem - cap_mem),
                max(0.0, used_disk - fd + need_disk - cap_disk),
                max(0.0, used_net - fn + need_net - cap_net))

    candidates = preemptible_allocs(job_priority, proposed)
    if not candidates:
        return None
    freed = (0.0, 0.0, 0.0, 0.0)
    victims: List[Allocation] = []
    remaining = list(candidates)
    while any(s > 0 for s in shortfall(freed)):
        if not remaining:
            return None
        short = shortfall(freed)
        remaining.sort(key=lambda a: victim_distance(short, _usage(a)))
        pick = remaining.pop(0)
        victims.append(pick)
        c, m, d, nw = _usage(pick)
        freed = (freed[0] + c, freed[1] + m, freed[2] + d, freed[3] + nw)

    # redundancy filter: drop any victim whose resources are not needed
    # once the rest are evicted (check highest-priority victims first so
    # the cheapest evictions survive)
    def covers_without(trial):
        fc = sum(_usage(v)[0] for v in trial)
        fm = sum(_usage(v)[1] for v in trial)
        fd = sum(_usage(v)[2] for v in trial)
        fn = sum(_usage(v)[3] for v in trial)
        return not any(s > 0 for s in shortfall((fc, fm, fd, fn)))

    pruned = prune_superset(
        victims, covers_without,
        order_key=lambda v: -(v.job.priority if v.job else 50))
    return pruned or None


def group_preemptible(job_priority: int, allocs: Sequence[Allocation]
                      ) -> List[List[Allocation]]:
    """Victim candidates grouped by job priority, lowest group first
    (reference: filterAndGroupPreemptibleAllocs :663)."""
    by_prio: Dict[int, List[Allocation]] = {}
    for a in allocs:
        if a.terminal_status() or a.job is None:
            continue
        if job_priority - a.job.priority < PRIORITY_DELTA:
            continue
        by_prio.setdefault(a.job.priority, []).append(a)
    return [by_prio[p] for p in sorted(by_prio)]


def _first_network(alloc: Allocation):
    nets = alloc.comparable_resources().networks
    return nets[0] if nets else None


def preempt_for_network(job_priority: int, proposed: Sequence[Allocation],
                        ask_net, node: Node
                        ) -> Optional[List[Allocation]]:
    """Find victims freeing bandwidth / reserved ports for one network
    ask (reference: PreemptForNetwork :270).  Victims must share the
    ask's network DEVICE; a needed reserved port held by a
    non-preemptible alloc disqualifies the whole device.  Within a
    device, victims are taken lowest-priority-first, closest MBits
    first (networkResourceDistance :627), until the ask fits; a final
    pass drops superset victims."""
    from ..structs.network import NetworkIndex

    if not proposed:
        return None
    mbits_needed = int(ask_net.mbits)
    ports_needed = [p.value for p in ask_net.reserved_ports]

    ni = NetworkIndex()
    ni.set_node(node)
    ni.add_allocs(proposed)

    device_allocs: Dict[str, List[Allocation]] = {}
    filtered_ports: Dict[str, set] = {}
    for a in proposed:
        if a.terminal_status() or a.job is None:
            continue
        net = _first_network(a)
        if net is None:
            continue
        if job_priority - a.job.priority < PRIORITY_DELTA:
            for pt in net.reserved_ports:
                filtered_ports.setdefault(net.device, set()).add(pt.value)
            continue
        device_allocs.setdefault(net.device, []).append(a)
    if not device_allocs:
        return None

    def net_distance(used_mbits: float) -> float:
        if mbits_needed <= 0:
            return float("inf")
        return abs((mbits_needed - used_mbits) / mbits_needed)

    for device, current in device_allocs.items():
        total_bw = ni.avail_bandwidth.get(device, 0)
        if total_bw < mbits_needed:
            continue
        free_bw = total_bw - ni.used_bandwidth.get(device, 0)
        victims: List[Allocation] = []
        preempted_bw = 0

        if ports_needed:
            used_port_to_alloc = {}
            for a in current:
                for n in a.comparable_resources().networks:
                    for pt in n.reserved_ports:
                        used_port_to_alloc[pt.value] = a
            blocked = False
            for port in ports_needed:
                holder = used_port_to_alloc.get(port)
                if holder is not None:
                    if holder not in victims:
                        net = _first_network(holder)
                        preempted_bw += int(net.mbits) if net else 0
                        victims.append(holder)
                elif port in filtered_ports.get(device, ()):
                    blocked = True        # higher-priority holder
                    break
            if blocked:
                continue
            current = [a for a in current if a not in victims]

        met = preempted_bw + free_bw >= mbits_needed
        if not met:
            bw = {"freed": preempted_bw}

            def charge(a):
                net = _first_network(a)
                bw["freed"] += int(net.mbits) if net else 0

            taken, met = take_from_groups(
                job_priority, current,
                met=lambda: bw["freed"] + free_bw >= mbits_needed,
                charge=charge,
                order_key=lambda a: net_distance(
                    _first_network(a).mbits if _first_network(a) else 0))
            victims.extend(taken)
            preempted_bw = bw["freed"]
        if not met:
            continue
        # superset filter: drop victims (largest distance first) whose
        # bandwidth is not needed once the rest are evicted, keeping
        # reserved-port holders (their eviction is what frees the port)
        port_holders = set()
        for a in victims:
            net = _first_network(a)
            if net and any(p.value in ports_needed
                           for p in net.reserved_ports):
                port_holders.add(a.id)

        def covers_without(trial):
            freed = sum(int(_first_network(v).mbits)
                        for v in trial if _first_network(v))
            return freed + free_bw >= mbits_needed

        pruned = prune_superset(
            victims, covers_without,
            order_key=lambda v: -net_distance(
                _first_network(v).mbits if _first_network(v) else 0),
            protected=frozenset(port_holders))
        return pruned or None
    return None


def preempt_for_device(job_priority: int, proposed: Sequence[Allocation],
                       ask, node: Node, extra_needed: int = 0
                       ) -> Optional[List[Allocation]]:
    """Find victims freeing device instances for one device ask
    (reference: PreemptForDevice :472).  Allocations are grouped by the
    device group they hold instances of; per group, victims accumulate
    lowest-priority-first until freed + free >= ask.count; across groups
    the option with the smallest net priority (sum of unique victim
    priorities) wins (selectBestAllocs :559).  Device-attribute
    constraints on the ask are not re-checked here (the solver's device
    dimension already filtered candidate nodes)."""
    from ..structs.devices import DeviceAccounter

    acct = DeviceAccounter(node)
    acct.add_allocs(proposed)

    matching = {dev.id_tuple() for dev in node.node_resources.devices
                if ask.matches(*dev.id_tuple())}
    if not matching:
        return None

    # device group -> (allocs using it, instance count per alloc)
    group_use: Dict[Tuple[str, str, str],
                    Tuple[List[Allocation], Dict[str, int]]] = {}
    for a in proposed:
        if a.terminal_status() or a.job is None:
            continue
        for tr in a.allocated_resources.tasks.values():
            for ad in tr.devices:
                key = (ad.vendor, ad.type, ad.name)
                if key not in matching:
                    continue
                allocs, counts = group_use.setdefault(key, ([], {}))
                if a.id not in counts:
                    allocs.append(a)
                counts[a.id] = counts.get(a.id, 0) + len(ad.device_ids)

    needed = int(ask.count) + int(extra_needed)
    options: List[Tuple[List[Allocation], Dict[str, int]]] = []
    for key, (allocs, counts) in group_use.items():
        free = len(acct.free_instances(*key))
        got = {"n": 0}
        picked, enough = take_from_groups(
            job_priority, allocs,
            met=lambda: got["n"] + free >= needed,
            charge=lambda a: got.__setitem__("n", got["n"] + counts[a.id]))
        if enough:
            options.append((picked, counts))
    if not options:
        return None

    # selectBestAllocs: within an option, biggest instance holders
    # first, trimmed at the needed count; lowest net priority wins
    best: Optional[List[Allocation]] = None
    best_prio = float("inf")
    for allocs, counts in options:
        allocs = sorted(allocs, key=lambda a: -counts[a.id])
        picked, prios, got = [], set(), 0
        for a in allocs:
            if got >= needed:
                break
            got += counts[a.id]
            picked.append(a)
            prios.add(a.job.priority)
        net_priority = sum(prios)
        if net_priority < best_prio:
            best_prio = net_priority
            best = picked
    return best


def free_device_instances_by_group(node: Node,
                                   allocs: Sequence[Allocation], ask
                                   ) -> Dict[Tuple[str, str, str],
                                             List[str]]:
    """Free matching instance ids per device GROUP given the current
    allocs — device asks must be satisfied within a single group
    (solve.py _assign_devices), so callers look at the per-group max,
    not a cross-group sum."""
    from ..structs.devices import DeviceAccounter
    acct = DeviceAccounter(node)
    acct.add_allocs(allocs)
    out: Dict[Tuple[str, str, str], List[str]] = {}
    for dev in node.node_resources.devices:
        if ask.matches(*dev.id_tuple()):
            out[dev.id_tuple()] = acct.free_instances(*dev.id_tuple())
    return out


def find_preemption(node: Node, proposed: Sequence[Allocation], job,
                    tg) -> Optional[List[Allocation]]:
    """Full preemption pass for one (node, task group): task-group
    resources first, then network asks, then device asks — each pass
    only runs when the group actually requests that dimension, and later
    passes see earlier victims as already evicted (the reference runs
    the analogous passes inside BinPackIterator as each dimension fails:
    PreemptForTaskGroup :198, PreemptForNetwork :270,
    PreemptForDevice :472)."""
    from ..solver.tensorize import group_resource_vector

    from ..structs import (AllocatedResources, AllocatedTaskResources,
                           NetworkResource)

    vec = group_resource_vector(tg)
    victims = list(pick_victims(node, proposed, job.priority,
                                float(vec[0]), float(vec[1]),
                                float(vec[2]), float(vec[3])) or [])
    victim_ids = {v.id for v in victims}
    remaining = [a for a in proposed if a.id not in victim_ids]

    # The group's OWN earlier asks consume capacity the later passes
    # must see: modelled as a job-less in-flight alloc (counts toward
    # usage, never a victim) that grows as asks are processed.
    pending_nets: List[NetworkResource] = []
    net_asks = list(tg.networks)
    for t in tg.tasks:
        net_asks.extend(t.resources.networks)
    for net in net_asks:
        if not (net.mbits or net.reserved_ports):
            continue
        probe_pool = list(remaining)
        if pending_nets:
            probe_pool.append(Allocation(
                id="_pending", allocated_resources=AllocatedResources(
                    tasks={"_pending": AllocatedTaskResources(
                        networks=list(pending_nets))})))
        nv = preempt_for_network(job.priority, probe_pool, net, node)
        if nv:
            victims.extend(nv)
            victim_ids |= {v.id for v in nv}
            remaining = [a for a in remaining if a.id not in victim_ids]
        pending_nets.append(NetworkResource(
            device=net.device or "", mbits=net.mbits,
            reserved_ports=list(net.reserved_ports)))

    pending_dev = 0        # instances asked so far by this group
    for t in tg.tasks:
        for d in t.resources.devices:
            need = int(d.count) + pending_dev
            free_by_grp = free_device_instances_by_group(
                node, remaining, d)
            if any(len(f) >= need for f in free_by_grp.values()):
                pending_dev += int(d.count)
                continue
            dv = preempt_for_device(job.priority, remaining, d, node,
                                    extra_needed=pending_dev)
            if dv:
                victims.extend(dv)
                victim_ids |= {v.id for v in dv}
                remaining = [a for a in remaining
                             if a.id not in victim_ids]
            pending_dev += int(d.count)
    return victims or None


def preemption_enabled(config, sched_type: str) -> bool:
    if config is None:
        return sched_type == "system"
    return {
        "system": config.preemption_system_enabled,
        "service": config.preemption_service_enabled,
        "batch": config.preemption_batch_enabled,
    }.get(sched_type, False)
