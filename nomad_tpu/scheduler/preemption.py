"""Preemption: evict lower-priority allocs to make room.

Reference semantics: scheduler/preemption.go — Preemptor :96,
PreemptForTaskGroup :198, resource-distance scoring
`basicResourceDistance` :608, priority grouping with delta >= 10
`filterAndGroupPreemptibleAllocs` :663, redundant-victim filtering :702.

Host-side second pass: the device solve surfaces which placements
exhausted resources on otherwise-feasible nodes; this module picks the
minimum-distance victim set per candidate node.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..structs import Allocation, ComparableResources, Node

PRIORITY_DELTA = 10


def resource_distance(delta_cpu: float, delta_mem: float, delta_disk: float,
                      delta_net: float) -> float:
    """Normalized euclidean distance between a victim's resources and the
    still-needed resources (reference: basicResourceDistance :608)."""
    return (delta_cpu ** 2 + delta_mem ** 2 + delta_disk ** 2
            + delta_net ** 2) ** 0.5


def _usage(alloc: Allocation) -> Tuple[float, float, float, float]:
    c = alloc.comparable_resources()
    return (float(c.cpu), float(c.memory_mb), float(c.disk_mb),
            float(sum(n.mbits for n in c.networks)))


def preemptible_allocs(job_priority: int, allocs: Sequence[Allocation]
                       ) -> List[Allocation]:
    """Victim candidates: non-terminal allocs at least PRIORITY_DELTA
    lower priority, lowest priority first."""
    out = []
    for a in allocs:
        if a.terminal_status():
            continue
        if a.job is None:
            # placeholder/probe allocs without a job snapshot have no
            # knowable priority — never victims
            continue
        prio = a.job.priority
        if job_priority - prio >= PRIORITY_DELTA:
            out.append((prio, a))
    out.sort(key=lambda t: (t[0], t[1].create_index))
    return [a for _p, a in out]


def pick_victims(node: Node, proposed: Sequence[Allocation],
                 job_priority: int, need_cpu: float, need_mem: float,
                 need_disk: float, need_net: float
                 ) -> Optional[List[Allocation]]:
    """Greedy minimum-distance victim selection on one node: repeatedly
    take the candidate closest to the remaining shortfall until the ask
    fits, then drop victims made redundant by later picks (reference:
    PreemptForTaskGroup :198 + :702)."""
    res = node.comparable_resources()
    reserved = node.comparable_reserved_resources()
    used_cpu = float(reserved.cpu)
    used_mem = float(reserved.memory_mb)
    used_disk = float(reserved.disk_mb)
    used_net = 0.0
    for a in proposed:
        c, m, d, nw = _usage(a)
        used_cpu += c
        used_mem += m
        used_disk += d
        used_net += nw
    cap_cpu = float(res.cpu)
    cap_mem = float(res.memory_mb)
    cap_disk = float(res.disk_mb)
    cap_net = float(sum(n.mbits for n in res.networks))

    def shortfall(freed):
        fc, fm, fd, fn = freed
        return (max(0.0, used_cpu - fc + need_cpu - cap_cpu),
                max(0.0, used_mem - fm + need_mem - cap_mem),
                max(0.0, used_disk - fd + need_disk - cap_disk),
                max(0.0, used_net - fn + need_net - cap_net))

    candidates = preemptible_allocs(job_priority, proposed)
    if not candidates:
        return None
    freed = (0.0, 0.0, 0.0, 0.0)
    victims: List[Allocation] = []
    remaining = list(candidates)
    while any(s > 0 for s in shortfall(freed)):
        if not remaining:
            return None
        sc, sm, sd, sn = shortfall(freed)
        norm = (max(sc, 1.0), max(sm, 1.0), max(sd, 1.0), max(sn, 1.0))

        def dist(a: Allocation) -> float:
            c, m, d, nw = _usage(a)
            return resource_distance((sc - c) / norm[0], (sm - m) / norm[1],
                                     (sd - d) / norm[2], (sn - nw) / norm[3])
        remaining.sort(key=dist)
        pick = remaining.pop(0)
        victims.append(pick)
        c, m, d, nw = _usage(pick)
        freed = (freed[0] + c, freed[1] + m, freed[2] + d, freed[3] + nw)

    # redundancy filter: drop any victim whose resources are not needed
    # once the rest are evicted (check highest-priority victims first so
    # the cheapest evictions survive)
    pruned = list(victims)
    for a in sorted(victims,
                    key=lambda v: -(v.job.priority if v.job else 50)):
        trial = [v for v in pruned if v.id != a.id]
        fc = sum(_usage(v)[0] for v in trial)
        fm = sum(_usage(v)[1] for v in trial)
        fd = sum(_usage(v)[2] for v in trial)
        fn = sum(_usage(v)[3] for v in trial)
        if not any(s > 0 for s in shortfall((fc, fm, fd, fn))):
            pruned = trial
    return pruned or None


def preemption_enabled(config, sched_type: str) -> bool:
    if config is None:
        return sched_type == "system"
    return {
        "system": config.preemption_system_enabled,
        "service": config.preemption_service_enabled,
        "batch": config.preemption_batch_enabled,
    }.get(sched_type, False)
