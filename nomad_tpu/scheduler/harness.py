"""In-process scheduler test harness.

Runs any scheduler against a real StateStore with a fake Planner that
applies plans directly — no raft, no RPC, no goroutines (reference:
scheduler/testing.go:42 Harness, SubmitPlan :80, RejectPlan :17).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from ..state.store import StateStore
from ..structs import Evaluation, Plan, PlanResult
from .base import new_scheduler


class Harness:
    def __init__(self, store: Optional[StateStore] = None):
        self.store = store or StateStore()
        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.create_evals: List[Evaluation] = []
        self.reblock_evals: List[Evaluation] = []
        self.reject_plan = False
        self.solver = None      # optional shared Solver (worker parity)
        self._lock = threading.Lock()
        self._index = self.store.latest_index() or 1000

    def next_index(self) -> int:
        with self._lock:
            self._index += 1
            return self._index

    # ---- Planner interface ----
    def submit_plan(self, plan: Plan) -> Tuple[Optional[PlanResult], object]:
        self.plans.append(plan)
        if self.reject_plan:
            # refresh-and-retry path: hand back a fresh snapshot
            return PlanResult(), self.store.snapshot()
        index = self.next_index()
        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=index)
        self.store.upsert_plan_results(index, result, plan.job)
        if self.solver is not None:
            # mirror the worker's plan-apply feed into the resident world
            self.solver.note_plan_result(plan, result)
        return result, None

    def update_eval(self, evaluation: Evaluation) -> None:
        self.evals.append(evaluation)

    def create_eval(self, evaluation: Evaluation) -> None:
        self.create_evals.append(evaluation)

    def reblock_eval(self, evaluation: Evaluation) -> None:
        self.reblock_evals.append(evaluation)

    # ---- driving ----
    def process(self, sched_type: str, evaluation: Evaluation):
        sched = new_scheduler(sched_type, self.store, self,
                              solver=self.solver)
        return sched.process(evaluation)
