"""Scheduling layer: reconciler, generic/system schedulers, harness.

Reference analog: scheduler/ package (SURVEY §2.1). The placement solve
itself lives in nomad_tpu.solver (the TPU plane); this package is the
host-side behavior around it.
"""
from .base import new_scheduler  # noqa: F401
