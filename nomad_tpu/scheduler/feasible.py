"""Host-side (scalar) feasibility semantics — the golden reference the TPU
mask kernels are differential-tested against, and the fallback path for
singleton evals.

Reference: scheduler/feasible.go — constraint operand zoo `checkConstraint`
:671, version parsing :694-706, DriverChecker :319, HostVolumeChecker :117,
DeviceChecker :1059, FeasibilityWrapper computed-class memoization :915.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..structs import (CONSTRAINT_ATTR_IS_NOT_SET, CONSTRAINT_ATTR_IS_SET,
                       CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY,
                       CONSTRAINT_REGEX, CONSTRAINT_SEMVER,
                       CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL,
                       CONSTRAINT_SET_CONTAINS_ANY, CONSTRAINT_VERSION,
                       Constraint, Node, TaskGroup, resolve_node_target)

_REGEX_CACHE: Dict[str, Optional[re.Pattern]] = {}
_VERSION_CACHE: Dict[str, Optional[list]] = {}


# --- version constraint handling (reference: helper go-version semantics) ---

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)([-.]?(?:[0-9A-Za-z-]+(?:\.[0-9A-Za-z-]+)*))?"
    r"(?:\+([0-9A-Za-z-]+(?:\.[0-9A-Za-z-]+)*))?$")


def parse_version(s: str):
    """Parse into (segments tuple, prerelease) or None."""
    m = _VERSION_RE.match(s.strip())
    if not m:
        return None
    segs = [int(p) for p in m.group(1).split(".")]
    while len(segs) < 3:
        segs.append(0)
    pre = m.group(2) or ""
    if pre.startswith("-") or pre.startswith("."):
        pre = pre[1:]
    return tuple(segs), pre


def _cmp_version(a, b) -> int:
    (sa, pa), (sb, pb) = a, b
    # compare numeric segments
    if sa != sb:
        return -1 if sa < sb else 1
    # a version WITH prerelease sorts before one without
    if pa == pb:
        return 0
    if pa == "":
        return 1
    if pb == "":
        return -1
    return -1 if pa < pb else 1


_CONSTRAINT_OP_RE = re.compile(r"^\s*(>=|<=|!=|~>|=|>|<)?\s*(.+?)\s*$")


def parse_version_constraint(expr: str):
    """Parse ">= 1.0, < 2.0" style expressions into [(op, version), ...]."""
    out = []
    for part in expr.split(","):
        m = _CONSTRAINT_OP_RE.match(part)
        if not m:
            return None
        op = m.group(1) or "="
        ver = parse_version(m.group(2))
        if ver is None:
            return None
        out.append((op, ver, m.group(2)))
    return out


_SEMVER_RE = re.compile(
    r"^(\d+)\.(\d+)\.(\d+)(?:-([0-9A-Za-z-]+(?:\.[0-9A-Za-z-]+)*))?"
    r"(?:\+([0-9A-Za-z-]+(?:\.[0-9A-Za-z-]+)*))?$")


def parse_semver(s: str):
    """Strict Semver 2.0 parse: exactly MAJOR.MINOR.PATCH, no 'v' prefix
    (reference: helper/constraints/semver — 'only accept properly
    formatted Semver versions')."""
    m = _SEMVER_RE.match(s.strip())
    if not m:
        return None
    return (int(m.group(1)), int(m.group(2)), int(m.group(3))), m.group(4) or ""


def parse_semver_constraint(expr: str):
    out = []
    for part in expr.split(","):
        m = _CONSTRAINT_OP_RE.match(part)
        if not m:
            return None
        op = m.group(1) or "="
        ver = parse_semver(m.group(2))
        if ver is None:
            return None
        out.append((op, ver, m.group(2)))
    return out


def check_version_match(lval: str, constraint_expr: str,
                        strict_semver: bool = False) -> bool:
    key = ("s:" if strict_semver else "v:") + constraint_expr
    parsed = _VERSION_CACHE.get(key)
    if key not in _VERSION_CACHE:
        parsed = (parse_semver_constraint(constraint_expr) if strict_semver
                  else parse_version_constraint(constraint_expr))
        _VERSION_CACHE[key] = parsed
    if parsed is None:
        return False
    ver = (parse_semver(str(lval)) if strict_semver
           else parse_version(str(lval)))
    if ver is None:
        return False
    for op, cver, raw in parsed:
        # prerelease gate (go-version constraint.go prereleaseCheck): a
        # non-prerelease constraint never matches a prerelease version; a
        # prerelease constraint only matches prereleases with equal base.
        v_pre, c_pre = ver[1] != "", cver[1] != ""
        if not c_pre and v_pre:
            return False
        if c_pre and v_pre and ver[0] != cver[0]:
            return False
        c = _cmp_version(ver, cver)
        if op == "=" and c != 0:
            return False
        if op == "!=" and c == 0:
            return False
        if op == ">" and c <= 0:
            return False
        if op == ">=" and c < 0:
            return False
        if op == "<" and c >= 0:
            return False
        if op == "<=" and c > 0:
            return False
        if op == "~>":
            # pessimistic: >= cver and < next significant release
            if c < 0:
                return False
            raw_segs = raw.strip().lstrip("v").split("-")[0].split(".")
            n = len(raw_segs)
            if n >= 2:
                upper = list(cver[0])
                upper[n - 2] += 1
                for i in range(n - 1, len(upper)):
                    upper[i] = 0
                if not _cmp_version(ver, (tuple(upper), "")) < 0:
                    return False
    return True


def check_regexp_match(lval: str, pattern: str) -> bool:
    pat = _REGEX_CACHE.get(pattern)
    if pattern not in _REGEX_CACHE:
        try:
            pat = re.compile(pattern)
        except re.error:
            pat = None
        _REGEX_CACHE[pattern] = pat
    if pat is None:
        return False
    return pat.search(str(lval)) is not None


def check_set_contains_all(lval: str, rval: str) -> bool:
    have = {p.strip() for p in str(lval).split(",")}
    need = [p.strip() for p in str(rval).split(",")]
    return all(n in have for n in need)


def check_set_contains_any(lval: str, rval: str) -> bool:
    have = {p.strip() for p in str(lval).split(",")}
    need = [p.strip() for p in str(rval).split(",")]
    return any(n in have for n in need)


def check_lexical_order(operand: str, lval: str, rval: str) -> bool:
    lval, rval = str(lval), str(rval)
    if operand == "<":
        return lval < rval
    if operand == "<=":
        return lval <= rval
    if operand == ">":
        return lval > rval
    if operand == ">=":
        return lval >= rval
    return False


def check_constraint(operand: str, lval, rval, lfound: bool,
                     rfound: bool) -> bool:
    """Reference: scheduler/feasible.go:671 checkConstraint."""
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return True  # handled by dedicated iterators
    if operand in ("=", "==", "is"):
        return lfound and rfound and str(lval) == str(rval)
    if operand in ("!=", "not"):
        return not (lfound and rfound and str(lval) == str(rval))
    if operand in ("<", "<=", ">", ">="):
        return lfound and rfound and check_lexical_order(operand, lval, rval)
    if operand == CONSTRAINT_ATTR_IS_SET:
        return lfound
    if operand == CONSTRAINT_ATTR_IS_NOT_SET:
        return not lfound
    if operand == CONSTRAINT_VERSION:
        return lfound and rfound and check_version_match(lval, str(rval))
    if operand == CONSTRAINT_SEMVER:
        return lfound and rfound and check_version_match(lval, str(rval),
                                                         strict_semver=True)
    if operand == CONSTRAINT_REGEX:
        return lfound and rfound and check_regexp_match(lval, str(rval))
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        return lfound and rfound and check_set_contains_all(lval, str(rval))
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        return lfound and rfound and check_set_contains_any(lval, str(rval))
    return False


def check_affinity(operand: str, lval, rval, lfound: bool, rfound: bool) -> bool:
    return check_constraint(operand, lval, rval, lfound, rfound)


def node_meets_constraint(node: Node, c: Constraint) -> bool:
    lval, lok = _resolve(node, c.ltarget)
    rval, rok = _resolve(node, c.rtarget)
    return check_constraint(c.operand, lval, rval, lok, rok)


def _resolve(node: Node, target: str):
    if target and target.startswith("${"):
        return resolve_node_target(node, target)
    # literal operand
    return target, target != ""


def driver_feasible(node: Node, driver: str) -> bool:
    """Reference: DriverChecker (feasible.go:319) — driver health via node
    driver info, falling back to the legacy `driver.<name>` attribute."""
    info = node.drivers.get(driver)
    if info is not None:
        return info.detected and info.healthy
    raw = node.attributes.get(f"driver.{driver}", "")
    if raw in ("1", "true"):
        return True
    return False


def merged_constraints(job, tg: TaskGroup) -> List[Constraint]:
    """Job + group + per-task constraints plus implicit driver checks,
    deduplicated (reference: stack.go SetJob/Select wiring)."""
    seen = set()
    out: List[Constraint] = []

    def _add(c: Constraint):
        if c.key() not in seen:
            seen.add(c.key())
            out.append(c)

    for c in job.constraints:
        _add(c)
    for c in tg.constraints:
        _add(c)
    for t in tg.tasks:
        for c in t.constraints:
            _add(c)
    return out


def group_drivers(tg: TaskGroup) -> List[str]:
    return sorted({t.driver for t in tg.tasks if t.driver})


def host_volumes_feasible(node: Node, tg: TaskGroup) -> bool:
    """Reference: HostVolumeChecker (feasible.go:117)."""
    for vol in tg.volumes.values():
        if vol.type not in ("", "host"):
            continue
        cfg = node.host_volumes.get(vol.source)
        if cfg is None:
            return False
        if not vol.read_only and cfg.read_only:
            return False
    return True


def devices_feasible(node: Node, tg: TaskGroup) -> Tuple[bool, str]:
    """Count-only device feasibility (reference: DeviceChecker
    feasible.go:1059). Per-instance selection happens at rank time."""
    asks: Dict[Tuple[str, str, str], int] = {}
    for t in tg.tasks:
        for d in t.resources.devices:
            asks[d.id_tuple()] = asks.get(d.id_tuple(), 0) + d.count
    if not asks:
        return True, ""
    from ..structs.resources import device_pattern_matches
    for key, want in asks.items():
        have = 0
        for dev in node.node_resources.devices:
            if device_pattern_matches(key, dev.id_tuple()):
                have += sum(1 for i in dev.instances if i.healthy)
        if have < want:
            v, ty, m = key
            return False, f"missing devices: {v}/{ty}/{m}"
    return True, ""


def group_feasible(node: Node, job, tg: TaskGroup) -> Tuple[bool, str]:
    """Full scalar feasibility for one (node, group): datacenter,
    constraints, drivers, host volumes, devices. Returns (ok, reason)."""
    if node.datacenter not in job.datacenters and "*" not in job.datacenters:
        return False, "datacenter not eligible"
    for c in merged_constraints(job, tg):
        if not node_meets_constraint(node, c):
            return False, str(c)
    for drv in group_drivers(tg):
        if not driver_feasible(node, drv):
            return False, f"missing drivers"
    if not host_volumes_feasible(node, tg):
        return False, "missing compatible host volumes"
    ok, why = devices_feasible(node, tg)
    if not ok:
        return False, why
    return True, ""
