"""Scheduler-shared utilities.

Reference semantics: scheduler/util.go — taintedNodes :312,
updateNonTerminalAllocsToLost :817, tasksUpdated :351,
adjustQueuedAllocations :788, retryMax :277.
"""
from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional, Tuple

from ..structs import (ALLOC_CLIENT_PENDING, ALLOC_CLIENT_RUNNING,
                       ALLOC_CLIENT_LOST, ALLOC_DESIRED_EVICT,
                       ALLOC_DESIRED_STOP, ALLOC_LOST, NODE_STATUS_DOWN,
                       Allocation, DeviceAccounter, Job, NetworkIndex, Node,
                       Plan, PlanResult, TaskGroup)


def tainted_nodes(snapshot, allocs: List[Allocation]
                  ) -> Dict[str, Optional[Node]]:
    """Map of node id -> node for nodes hosting these allocs that are
    down, draining, or deregistered (None)."""
    out: Dict[str, Optional[Node]] = {}
    seen = set()
    for a in allocs:
        if a.node_id in seen:
            continue
        seen.add(a.node_id)
        node = snapshot.node_by_id(a.node_id)
        if node is None:
            out[a.node_id] = None
        elif node.terminal_status() or node.drain:
            out[a.node_id] = node
    return out


def update_non_terminal_allocs_to_lost(plan: Plan,
                                       tainted: Dict[str, Optional[Node]],
                                       allocs: List[Allocation]) -> None:
    """Allocs already marked stop/evict whose client never acked, sitting
    on a dead node, are marked lost in the plan."""
    for a in allocs:
        if a.node_id not in tainted:
            continue
        node = tainted[a.node_id]
        if node is not None and node.status != NODE_STATUS_DOWN:
            continue
        if (a.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT)
                and a.client_status in (ALLOC_CLIENT_RUNNING,
                                        ALLOC_CLIENT_PENDING)):
            plan.append_stopped_alloc(a, ALLOC_LOST, ALLOC_CLIENT_LOST)


def tasks_updated(job_a: Job, job_b: Job, group: str) -> bool:
    """Whether the group changed in a way that needs a destructive update
    (reference: util.go:351)."""
    a = job_a.lookup_task_group(group)
    b = job_b.lookup_task_group(group)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if _nets_updated(a.networks, b.networks):
        return True
    if {k: v.__dict__ for k, v in a.volumes.items()} != \
            {k: v.__dict__ for k, v in b.volumes.items()}:
        return True
    for at in a.tasks:
        bt = b.lookup_task(at.name)
        if bt is None:
            return True
        if at.driver != bt.driver or at.user != bt.user:
            return True
        if at.config != bt.config or at.env != bt.env:
            return True
        if ([x.__dict__ for x in at.artifacts]
                != [x.__dict__ for x in bt.artifacts]):
            return True
        if at.meta != bt.meta:
            return True
        if ([t.__dict__ for t in at.templates]
                != [t.__dict__ for t in bt.templates]):
            return True
        ar, br = at.resources, bt.resources
        if ar.cpu != br.cpu or ar.memory_mb != br.memory_mb:
            return True
        if _nets_updated(ar.networks, br.networks):
            return True
        if ([d.__dict__ for d in ar.devices]
                != [d.__dict__ for d in br.devices]):
            return True
    return False


def _nets_updated(a, b) -> bool:
    if len(a) != len(b):
        return True
    for an, bn in zip(a, b):
        if an.mbits != bn.mbits:
            return True
        if len(an.dynamic_ports) != len(bn.dynamic_ports):
            return True
        if ({(p.label, p.value, p.to) for p in an.reserved_ports}
                != {(p.label, p.value, p.to) for p in bn.reserved_ports}):
            return True
    return False


def adjust_queued_allocations(result: Optional[PlanResult],
                              queued: Dict[str, int]) -> None:
    """Decrement queued counts by what the plan actually placed."""
    if result is None:
        return
    for allocs in result.node_allocation.values():
        for a in allocs:
            # only count allocations created by this plan
            if result.alloc_index and a.create_index != result.alloc_index:
                continue
            if a.task_group in queued:
                queued[a.task_group] = max(0, queued[a.task_group] - 1)


def retry_max(limit: int, fn: Callable[[], Tuple[bool, object]],
              reset_fn: Optional[Callable[[], bool]] = None):
    """Run fn up to `limit` times, resetting the attempt budget whenever
    reset_fn reports progress (reference: util.go:277)."""
    attempts = 0
    while attempts < limit:
        done, err = fn()
        if err is not None:
            return err
        if done:
            return None
        if reset_fn is not None and reset_fn():
            attempts = 0
        else:
            attempts += 1
    return "max-retries"


def in_place_fits(snapshot, existing: Allocation, job: Job, tg: TaskGroup,
                  plan: Plan) -> Optional[Allocation]:
    """Can `existing` be updated in place on its node? Returns the updated
    allocation (new job/resources) or None (reference: util.go:552
    inplaceUpdate — re-checks feasibility and fit against proposed state
    minus the alloc itself)."""
    from . import feasible as hostfeas
    from ..solver.solve import Solver
    from ..solver.tensorize import PlacementAsk

    node = snapshot.node_by_id(existing.node_id)
    if node is None:
        return None
    ok, _reason = hostfeas.group_feasible(node, job, tg)
    if not ok:
        return None

    # proposed allocs on the node: live state minus plan stops minus self
    stopped = {a.id for allocs in plan.node_update.values() for a in allocs}
    proposed = [a for a in snapshot.allocs_by_node(node.id)
                if not a.terminal_status()
                and a.id not in stopped and a.id != existing.id]
    proposed.extend(plan.node_allocation.get(node.id, []))

    out = Solver._host_commit(node, 0, PlacementAsk(job=job, tg=tg, count=1),
                              {}, {}, {node.id: proposed})
    if out is None:
        return None

    # total cpu/mem/disk must still fit alongside the other allocs
    from ..structs.funcs import allocs_fit
    updated = copy.copy(existing)
    updated.job = job
    updated.allocated_resources = out
    fit, _dim, _used = allocs_fit(node, proposed + [updated])
    if not fit:
        return None
    return updated


def generic_alloc_update_fn(snapshot, plan: Plan):
    """Build the reconciler's alloc_update_fn closure
    (reference: util.go:846 genericAllocUpdateFn)."""
    def update_fn(existing: Allocation, new_job: Job, new_tg: TaskGroup
                  ) -> Tuple[bool, bool, Optional[Allocation]]:
        # same version: nothing to do (reference: util.go:846 "Same
        # index, so nothing to do" — the check belongs HERE, not in the
        # reconciler, so tests can drive update decisions directly)
        if existing.job is not None and \
                existing.job.version == new_job.version:
            return True, False, None
        if existing.job is not None and tasks_updated(
                existing.job, new_job, new_tg.name):
            return False, True, None
        updated = in_place_fits(snapshot, existing, new_job, new_tg, plan)
        if updated is None:
            return False, True, None
        return False, False, updated
    return update_fn
