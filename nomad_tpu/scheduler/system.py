"""SystemScheduler: one alloc per eligible node (daemonset-style).

Reference: scheduler/system_sched.go (Process :54, computeJobAllocs :183,
computePlacements :268) and the per-node diff in scheduler/util.go:70
(diffSystemAllocsForNode). The TPU recast computes the feasibility mask
for all (group, node) pairs in one kernel call, then walks the per-node
placements host-side with running resource accounting.
"""
from __future__ import annotations

import copy
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..solver.solve import Solver
from ..solver.tensorize import PlacementAsk
from ..structs import (ALLOC_CLIENT_PENDING, ALLOC_DESIRED_RUN, ALLOC_LOST,
                       ALLOC_CLIENT_LOST, ALLOC_NODE_TAINTED,
                       ALLOC_NOT_NEEDED, ALLOC_UPDATING,
                       EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
                       EVAL_TRIGGER_JOB_REGISTER, EVAL_TRIGGER_JOB_DEREGISTER,
                       EVAL_TRIGGER_NODE_DRAIN, EVAL_TRIGGER_NODE_UPDATE,
                       EVAL_TRIGGER_ALLOC_STOP,
                       EVAL_TRIGGER_ROLLING_UPDATE, EVAL_TRIGGER_QUEUED_ALLOCS,
                       AllocMetric, Allocation, Evaluation, Job, Node, Plan,
                       TaskGroup)
from ..structs.funcs import allocs_fit, score_fit
from ..utils.ids import generate_uuid
from .util import (tainted_nodes, tasks_updated,
                   update_non_terminal_allocs_to_lost)

MAX_SYSTEM_ATTEMPTS = 5

_VALID_TRIGGERS = {
    EVAL_TRIGGER_JOB_REGISTER, EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_NODE_DRAIN, EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_ALLOC_STOP, EVAL_TRIGGER_ROLLING_UPDATE,
    EVAL_TRIGGER_QUEUED_ALLOCS,
}


@dataclass
class _SystemDiff:
    # (node, task group, alloc name, previous alloc being replaced or None)
    place: List[Tuple[Node, TaskGroup, str, Optional[Allocation]]] = field(
        default_factory=list)
    update: List[Tuple[Allocation, TaskGroup]] = field(default_factory=list)
    stop: List[Allocation] = field(default_factory=list)
    lost: List[Allocation] = field(default_factory=list)
    ignore: List[Allocation] = field(default_factory=list)


def diff_system_allocs(job: Optional[Job], ready_nodes: List[Node],
                       tainted: Dict[str, Optional[Node]],
                       allocs: List[Allocation]) -> _SystemDiff:
    """Per-node diff: each ready node should run exactly one alloc per task
    group (reference: util.go:70/:201)."""
    diff = _SystemDiff()
    required = {tg.name: tg for tg in job.task_groups} if job else {}
    eligible = {n.id: n for n in ready_nodes}

    by_node: Dict[str, List[Allocation]] = {}
    for a in allocs:
        by_node.setdefault(a.node_id, []).append(a)

    for nid, node_allocs in by_node.items():
        for a in node_allocs:
            tg = required.get(a.task_group)
            if tg is None or job is None or job.stopped():
                if not a.terminal_status():
                    diff.stop.append(a)
                continue
            if nid in tainted:
                node = tainted[nid]
                if a.terminal_status():
                    diff.ignore.append(a)
                elif node is None or node.terminal_status():
                    # node down/gone wins over a drainer mark: the alloc
                    # is lost, not politely stopped
                    diff.lost.append(a)
                elif a.desired_transition.should_migrate():
                    # drainer-marked on a live draining node: stop it
                    diff.stop.append(a)
                else:
                    # draining but not yet marked by the drainer: left
                    # alone — system allocs drain LAST
                    # (reference: util.go:96-127 goto IGNORE)
                    diff.ignore.append(a)
                continue
            # drainer-marked allocs elsewhere migrate (stop + replace)
            if (not a.terminal_status()
                    and a.desired_transition.should_migrate()):
                diff.stop.append(a)
                continue
            if nid not in eligible:
                # ineligible (but live) node: existing allocs are left
                # alone (reference: util.go:131-135 goto IGNORE)
                diff.ignore.append(a)
                continue
            if a.terminal_status():
                # terminal alloc on an eligible node: replaced below via
                # place (name reuse) unless the job version matches and it
                # ran to completion
                diff.ignore.append(a)
                continue
            if a.job is not None and a.job.job_modify_index != \
                    job.job_modify_index:
                if tasks_updated(a.job, job, tg.name):
                    diff.update.append((a, tg))
                else:
                    diff.ignore.append(a)
            else:
                diff.ignore.append(a)

    # placements: every eligible node lacking a live alloc per group
    live_by_node_tg = set()
    for a in allocs:
        if not a.terminal_status() or (a.job is not None
                                       and a.job.version == (job.version
                                                             if job else -1)
                                       and a.ran_successfully()):
            live_by_node_tg.add((a.node_id, a.task_group))
    if job is not None and not job.stopped():
        for n in ready_nodes:
            for name, tg in required.items():
                if (n.id, name) not in live_by_node_tg:
                    diff.place.append((n, tg, f"{job.id}.{name}[0]", None))
    return diff


class SystemScheduler:
    """Reference: system_sched.go:22."""

    def __init__(self, state, planner, solver: Optional[Solver] = None):
        self.state = state
        self.planner = planner
        self.solver = solver or Solver()
        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.failed_tg_allocs: Dict[str, AllocMetric] = {}
        self.queued_allocs: Dict[str, int] = {}

    def process(self, evaluation: Evaluation) -> Optional[str]:
        self.eval = evaluation
        if evaluation.triggered_by not in _VALID_TRIGGERS:
            self._set_status(EVAL_STATUS_FAILED,
                             f"scheduler cannot handle "
                             f"'{evaluation.triggered_by}'")
            return None
        attempts = 0
        err: Optional[str] = None
        done = False
        while attempts < MAX_SYSTEM_ATTEMPTS and not done:
            done, err = self._process()
            if err is not None:
                break
            attempts += 1
        if err is not None:
            self._set_status(EVAL_STATUS_FAILED, str(err))
            return err
        if not done:
            self._set_status(EVAL_STATUS_FAILED, "maximum attempts reached")
            return None
        self._set_status(EVAL_STATUS_COMPLETE, "")
        return None

    def _process(self) -> Tuple[bool, Optional[str]]:
        snapshot = (self.state.snapshot()
                    if hasattr(self.state, "snapshot") else self.state)
        ev = self.eval
        self.job = snapshot.job_by_id(ev.namespace, ev.job_id)
        self.failed_tg_allocs = {}
        self.queued_allocs = {tg.name: 0 for tg in
                              (self.job.task_groups if self.job else [])}
        self.plan = ev.make_plan(self.job)

        if self.job is not None and self.job.datacenters:
            nodes, by_dc = snapshot.ready_nodes_in_dcs(self.job.datacenters)
        else:
            nodes = [n for n in snapshot.nodes() if n.ready()]
            by_dc = {}
            for n in nodes:
                by_dc[n.datacenter] = by_dc.get(n.datacenter, 0) + 1

        allocs = snapshot.allocs_by_job(ev.namespace, ev.job_id)
        tainted = tainted_nodes(snapshot, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        diff = diff_system_allocs(self.job, nodes, tainted, allocs)

        for a in diff.stop:
            desc = (ALLOC_NODE_TAINTED if a.node_id in tainted
                    else ALLOC_NOT_NEEDED)
            self.plan.append_stopped_alloc(a, desc, "")
        for a in diff.lost:
            self.plan.append_stopped_alloc(a, ALLOC_LOST, ALLOC_CLIENT_LOST)
        # updates are destructive for system jobs: stop + replace in place
        for a, tg in diff.update:
            self.plan.append_stopped_alloc(a, ALLOC_UPDATING, "")
            node = snapshot.node_by_id(a.node_id)
            if node is not None and node.ready():
                diff.place.append((node, tg, a.name, a))

        for _n, tg, _name, _prev in diff.place:
            self.queued_allocs[tg.name] = self.queued_allocs.get(tg.name,
                                                                 0) + 1

        if diff.place:
            err = self._compute_placements(snapshot, nodes, by_dc, diff.place)
            if err is not None:
                return False, err

        if self.plan.is_no_op():
            return True, None
        result, new_state = self.planner.submit_plan(self.plan)
        if result is None:
            return False, "plan submission failed"
        if new_state is not None:
            self.state = new_state
            return False, None
        full, _e, _a = result.full_commit(self.plan)
        if not full:
            return False, None
        for allocs_ in result.node_allocation.values():
            for a in allocs_:
                if a.task_group in self.queued_allocs:
                    self.queued_allocs[a.task_group] = max(
                        0, self.queued_allocs[a.task_group] - 1)
        return True, None

    def _compute_placements(
            self, snapshot, nodes: List[Node], by_dc,
            place: List[Tuple[Node, TaskGroup, str, Optional[Allocation]]]
    ) -> Optional[str]:
        # one TPU feasibility pass over all (group, node) pairs
        groups = {tg.name: tg for _n, tg, _nm, _prev in place}
        asks = [PlacementAsk(job=self.job, tg=tg, count=0)
                for tg in groups.values()]
        ask_ix = {tg_name: g for g, tg_name in enumerate(groups)}
        pb = self.solver._tensorizer.pack(nodes, asks, None)
        from ..solver.masks import static_feasibility
        feas = static_feasibility(pb)
        node_ix = {n.id: i for i, n in enumerate(nodes)}

        stopped = {a.id for allocs in self.plan.node_update.values()
                   for a in allocs}
        usage: Dict[str, List[Allocation]] = {}
        for n in nodes:
            usage[n.id] = [a for a in snapshot.allocs_by_node(n.id)
                           if not a.terminal_status()
                           and a.id not in stopped]

        from .preemption import find_preemption, preemption_enabled
        preempt_ok = preemption_enabled(snapshot.scheduler_config(), "system")

        now = _time.time()
        for node, tg, name, prev in place:
            g = ask_ix[tg.name]
            i = node_ix[node.id]
            metric = AllocMetric()
            metric.nodes_evaluated = 1
            metric.nodes_available = dict(by_dc)
            if not bool(feas[g, i]):
                metric.filter_node(node.computed_class, "feasibility")
                self._record_failure(tg, metric)
                self._retract_stop(prev)
                continue
            resources = self.solver._host_commit(
                node, i, PlacementAsk(job=self.job, tg=tg, count=1),
                {}, {}, usage)
            victims = None
            if resources is None and preempt_ok:
                # ports / bandwidth / device instances exhausted: try
                # evicting lower-priority holders and re-commit
                victims = find_preemption(node, usage[node.id],
                                          self.job, tg)
                if victims:
                    victim_ids = {v.id for v in victims}
                    trial_usage = dict(usage)
                    trial_usage[node.id] = [a for a in usage[node.id]
                                            if a.id not in victim_ids]
                    resources = self.solver._host_commit(
                        node, i, PlacementAsk(job=self.job, tg=tg,
                                              count=1),
                        {}, {}, trial_usage)
                    if resources is not None:
                        usage[node.id] = trial_usage[node.id]
                    else:
                        victims = None
            if resources is None:
                metric.exhausted_node(node.id, node.computed_class, "network")
                self._record_failure(tg, metric)
                self._retract_stop(prev)
                continue
            probe = Allocation(id="probe", task_group=tg.name,
                               allocated_resources=resources)
            fit, dim, used = allocs_fit(node, usage[node.id] + [probe])
            if not fit and preempt_ok and victims is None:
                victims = find_preemption(node, usage[node.id],
                                          self.job, tg)
                if victims:
                    victim_ids = {v.id for v in victims}
                    trial = [a for a in usage[node.id]
                             if a.id not in victim_ids]
                    refit, rdim, rused = allocs_fit(node, trial + [probe])
                    if refit:
                        usage[node.id] = trial
                        fit, dim, used = refit, rdim, rused
                    else:
                        # evictions wouldn't help: keep usage untouched
                        victims = None
            if not fit:
                metric.exhausted_node(node.id, node.computed_class,
                                      dim or "resources")
                self._record_failure(tg, metric)
                self._retract_stop(prev)
                continue
            score = score_fit(node, used)
            metric.scores = {node.id: score}
            alloc = Allocation(
                id=generate_uuid(), namespace=self.eval.namespace,
                eval_id=self.eval.id, name=name, job_id=self.job.id,
                job=self.job, task_group=tg.name, node_id=node.id,
                node_name=node.name, allocated_resources=resources,
                metrics=metric, desired_status=ALLOC_DESIRED_RUN,
                client_status=ALLOC_CLIENT_PENDING,
                create_time=now, modify_time=now)
            if victims:
                alloc.preempted_allocations = sorted(v.id for v in victims)
                for v in victims:
                    self.plan.append_preempted_alloc(v, alloc.id)
            usage[node.id].append(alloc)
            self.plan.append_alloc(alloc)
        return None

    def _retract_stop(self, prev: Optional[Allocation]) -> None:
        """An update whose replacement failed keeps its old alloc running
        (reference: system_sched.go Plan.PopUpdate on placement failure)."""
        if prev is None:
            return
        lst = self.plan.node_update.get(prev.node_id, [])
        lst = [a for a in lst if a.id != prev.id]
        if lst:
            self.plan.node_update[prev.node_id] = lst
        else:
            self.plan.node_update.pop(prev.node_id, None)

    def _record_failure(self, tg: TaskGroup, metric: AllocMetric) -> None:
        existing = self.failed_tg_allocs.get(tg.name)
        if existing is not None:
            existing.coalesced_failures += 1
        else:
            self.failed_tg_allocs[tg.name] = metric

    def _set_status(self, status: str, description: str) -> None:
        ev = copy.copy(self.eval)
        ev.status = status
        ev.status_description = description
        ev.failed_tg_allocs = dict(self.failed_tg_allocs)
        ev.queued_allocations = dict(self.queued_allocs)
        self.planner.update_eval(ev)
