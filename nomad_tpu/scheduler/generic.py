"""GenericScheduler: service + batch scheduling through the TPU solver.

Per-eval flow mirrors the reference (scheduler/generic_sched.go:122 Process,
:213 process retry loop, :324 computeJobAllocs, :427 computePlacements) with
one structural change — the reference's per-placement iterator-chain solve
becomes a SINGLE batched Solver.solve() over all of the eval's placements,
the core of the TPU recast (SURVEY §7.1).
"""
from __future__ import annotations

import copy
import time as _time
from typing import Dict, List, Optional, Tuple

from ..solver.solve import LazyAllocsView, Solver
from ..solver.tensorize import PlacementAsk
from ..structs import (ALLOC_CLIENT_PENDING, ALLOC_DESIRED_RUN,
                       CONSTRAINT_DISTINCT_PROPERTY, EVAL_STATUS_BLOCKED,
                       EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
                       EVAL_TRIGGER_ALLOC_STOP, EVAL_TRIGGER_DEPLOYMENT_WATCHER,
                       EVAL_TRIGGER_DEPLOYMENT_PROMOTION,
                       EVAL_TRIGGER_FAILED_FOLLOW_UP,
                       EVAL_TRIGGER_JOB_DEREGISTER, EVAL_TRIGGER_JOB_REGISTER,
                       EVAL_TRIGGER_MAX_PLANS, EVAL_TRIGGER_NODE_DRAIN,
                       EVAL_TRIGGER_NODE_UPDATE, EVAL_TRIGGER_PERIODIC_JOB,
                       EVAL_TRIGGER_PREEMPTION, EVAL_TRIGGER_QUEUED_ALLOCS,
                       EVAL_TRIGGER_RETRY_FAILED_ALLOC,
                       EVAL_TRIGGER_ROLLING_UPDATE, EVAL_TRIGGER_SCALING,
                       AllocDeploymentStatus, Allocation, Evaluation, Job,
                       Plan, RescheduleEvent, RescheduleTracker, TaskGroup,
                       resolve_node_target)
from ..utils.ids import generate_uuid
from . import feasible as hostfeas
from .reconcile import (AllocDestructiveResult, AllocPlaceResult, Reconciler)
from .util import (adjust_queued_allocations, generic_alloc_update_fn,
                   tainted_nodes, update_non_terminal_allocs_to_lost)

MAX_SERVICE_ATTEMPTS = 5
MAX_BATCH_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS_DESC = "created to place remaining allocations"

_VALID_TRIGGERS = {
    EVAL_TRIGGER_JOB_REGISTER, EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_NODE_DRAIN, EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_ALLOC_STOP, EVAL_TRIGGER_ROLLING_UPDATE,
    EVAL_TRIGGER_QUEUED_ALLOCS, EVAL_TRIGGER_PERIODIC_JOB,
    EVAL_TRIGGER_MAX_PLANS, EVAL_TRIGGER_DEPLOYMENT_WATCHER,
    EVAL_TRIGGER_DEPLOYMENT_PROMOTION,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC, EVAL_TRIGGER_FAILED_FOLLOW_UP,
    EVAL_TRIGGER_PREEMPTION, EVAL_TRIGGER_SCALING,
}


class _Missing:
    """One pending placement: a reconciler place/destructive result bound
    to its task group."""

    def __init__(self, name: str, tg: TaskGroup,
                 previous: Optional[Allocation] = None,
                 reschedule: bool = False, canary: bool = False,
                 stop_previous: bool = False, stop_desc: str = ""):
        self.name = name
        self.tg = tg
        self.previous = previous
        self.reschedule = reschedule
        self.canary = canary
        self.stop_previous = stop_previous
        self.stop_desc = stop_desc


class GenericScheduler:
    """Schedules service and batch jobs (reference: generic_sched.go:77)."""

    def __init__(self, state, planner, batch: bool = False,
                 solver: Optional[Solver] = None):
        self.state = state
        self.planner = planner
        self.batch = batch
        self.solver = solver or Solver()

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result = None
        self.deployment = None
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: Dict[str, object] = {}
        self.queued_allocs: Dict[str, int] = {}
        self.followup_evals: List[Evaluation] = []
        self._class_eligibility: Dict[str, bool] = {}
        self._escaped = False

    # ------------------------------------------------------------------ API
    def process(self, evaluation: Evaluation) -> Optional[str]:
        self.eval = evaluation
        if evaluation.triggered_by not in _VALID_TRIGGERS:
            desc = f"scheduler cannot handle '{evaluation.triggered_by}'"
            self._set_status(EVAL_STATUS_FAILED, desc)
            return None

        limit = MAX_BATCH_ATTEMPTS if self.batch else MAX_SERVICE_ATTEMPTS
        progress = {"made": False}

        def once() -> Tuple[bool, Optional[str]]:
            progress["made"] = False
            done, err = self._process(progress)
            return done, err

        attempts = 0
        err: Optional[str] = None
        while attempts < limit:
            done, err = once()
            if err is not None or done:
                break
            attempts = 0 if progress["made"] else attempts + 1
        else:
            # retries exhausted: roll remaining work into a blocked eval
            if self.eval.status != EVAL_STATUS_BLOCKED:
                self._create_blocked_eval(planning_failure=True)
            err = "maximum attempts reached"
            self._set_status(EVAL_STATUS_FAILED, err)
            return None

        if err is not None:
            self._set_status(EVAL_STATUS_FAILED, str(err))
            return err
        self._set_status(EVAL_STATUS_COMPLETE, "")
        return None

    # ------------------------------------------------------------ internals
    def _process(self, progress) -> Tuple[bool, Optional[str]]:
        snapshot = (self.state.snapshot()
                    if hasattr(self.state, "snapshot") else self.state)
        missing, err = self._begin(self.eval, snapshot)
        if err is not None:
            return False, err
        if missing:
            err = self._compute_placements(snapshot, missing)
            if err is not None:
                return False, err
        return self._finalize(progress)

    def _begin(self, ev: Evaluation, snapshot
               ) -> Tuple[List["_Missing"], Optional[str]]:
        """Everything before the device solve: reconcile and assemble the
        plan skeleton. Returns the pending placements."""
        self.eval = ev
        self.snapshot = snapshot
        self.job = snapshot.job_by_id(ev.namespace, ev.job_id)
        self.failed_tg_allocs = {}
        self.queued_allocs = {}
        self.followup_evals = []
        self._sticky_probes = []
        self.plan = ev.make_plan(self.job)

        if not self.batch:
            self.deployment = snapshot.latest_deployment_by_job(
                ev.namespace, ev.job_id)
            if self.deployment is not None and not self.deployment.active():
                self.deployment = None
        else:
            self.deployment = None
        return self._compute_job_allocs(snapshot)

    def _finalize(self, progress) -> Tuple[bool, Optional[str]]:
        """Everything after the solve: blocked/follow-up evals and plan
        submission. Returns (done, err); not-done means retry."""
        ev = self.eval
        # blocked eval for any failed placements
        if (ev.status != EVAL_STATUS_BLOCKED and self.failed_tg_allocs
                and self.blocked is None):
            self._create_blocked_eval(planning_failure=False)

        # follow-up evals for delayed reschedules
        for fev in self.followup_evals:
            fev.previous_eval = ev.id
            self.planner.create_eval(fev)

        if self.plan.is_no_op() and not ev.annotate_plan:
            return True, None

        result, new_state = self.planner.submit_plan(self.plan)
        if result is None:
            return False, "plan submission failed"
        self.plan_result = result
        adjust_queued_allocations(result, self.queued_allocs)
        # progress = the applied result actually changed state (reference:
        # progressMade) — a bare snapshot refresh doesn't reset the budget
        progress["made"] = bool(result.node_update or result.node_allocation
                                or result.deployment
                                or result.deployment_updates)

        if new_state is not None:
            self.state = new_state
            return False, None
        full, _expected, _actual = result.full_commit(self.plan)
        if not full:
            return False, None
        return True, None

    def _compute_job_allocs(self, snapshot
                            ) -> Tuple[List["_Missing"], Optional[str]]:
        ev = self.eval
        allocs = snapshot.allocs_by_job(ev.namespace, ev.job_id)
        tainted = tainted_nodes(snapshot, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        reconciler = Reconciler(
            generic_alloc_update_fn(snapshot, self.plan), self.batch,
            ev.job_id, self.job, self.deployment, allocs, tainted, ev.id)
        results = reconciler.compute()

        if ev.annotate_plan:
            self.plan.annotations = {
                "desired_tg_updates": results.desired_tg_updates}

        self.plan.deployment = results.deployment
        self.plan.deployment_updates = results.deployment_updates
        if results.deployment is not None:
            self.deployment = results.deployment

        for group_evals in results.desired_followup_evals.values():
            self.followup_evals.extend(group_evals)

        for stop in results.stop:
            self.plan.append_stopped_alloc(stop.alloc, stop.status_description,
                                           stop.client_status)

        dep_id = self.deployment.id if self.deployment else ""
        for update in results.inplace_update:
            if update.deployment_id != dep_id:
                update.deployment_id = dep_id
                update.deployment_status = None
            self.plan.append_alloc(update)

        for update in results.attribute_updates.values():
            self.plan.append_alloc(update)

        if not results.place and not results.destructive_update:
            if self.job is not None:
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return [], None

        for p in results.place:
            self.queued_allocs[p.task_group.name] = \
                self.queued_allocs.get(p.task_group.name, 0) + 1
        for d in results.destructive_update:
            self.queued_allocs[d.place_task_group.name] = \
                self.queued_allocs.get(d.place_task_group.name, 0) + 1
        from ..utils.tracing import global_tracer as _tr
        _tr.event(ev.id, "schedule.reconcile",
                  n_place=len(results.place),
                  n_destructive=len(results.destructive_update),
                  n_stop=len(results.stop),
                  n_inplace=len(results.inplace_update))

        missing: List[_Missing] = []
        # destructive replacements go first so their capacity frees up for
        # the batch (reference passes destructive before place)
        for d in results.destructive_update:
            missing.append(_Missing(
                name=d.place_name, tg=d.place_task_group,
                previous=d.stop_alloc, stop_previous=True,
                stop_desc=d.stop_status_description))
        for p in results.place:
            missing.append(_Missing(
                name=p.name, tg=p.task_group, previous=p.previous_alloc,
                reschedule=p.reschedule, canary=p.canary))
        return missing, None

    # ----------------------------------------------------- placement solve
    def _compute_placements(self, snapshot, missing: List[_Missing]
                            ) -> Optional[str]:
        prep = self._prepare_placements(snapshot, missing)
        if prep is None:
            return None
        nodes, by_dc, allocs_by_node, asks, ask_missing = prep
        # proposed-state corrections for the solver's resident world:
        # this plan's eager stops and the sticky probes are the ONLY
        # places the proposed usage differs from the store-tracked one
        stops = [a for lst in self.plan.node_update.values()
                 for a in lst]
        from .preemption import preemption_enabled
        from ..utils.tracing import global_tracer as _tr
        preempt_ok = preemption_enabled(
            snapshot.scheduler_config(),
            "batch" if self.batch else "service")
        span = _tr.stage(self.eval.id, "solve",
                         job_id=self.eval.job_id, fused=False)
        out = self.solver.solve(
            nodes, asks, allocs_by_node, by_dc, snapshot=snapshot,
            proposed_delta=(stops, list(self._sticky_probes)),
            preempt=preempt_ok)
        self._consume_solve(snapshot, out, nodes, allocs_by_node, missing,
                            ask_missing, span=span)
        return None

    def _prepare_placements(self, snapshot, missing: List[_Missing],
                            nodes=None, by_dc=None, allocs_by_node=None,
                            node_by_id=None):
        """Pre-solve work: eager destructive stops, sticky placements and
        per-tg ask assembly. Returns (nodes, by_dc, allocs_by_node, asks,
        ask_missing), or None when nothing remains for the solver.
        The fleet path passes shared nodes/allocs_by_node/node_by_id so
        evals in one batch see the same world (and skip rebuilding the
        O(cluster) id map once per member)."""
        if self.job is None:
            return None
        if nodes is None:
            nodes, by_dc = snapshot.ready_nodes_in_dcs(self.job.datacenters)
        if not nodes:
            for m in missing:
                self._record_failure(m, None)
            self._stop_destructive_for_failed(missing, set())
            return None

        # stop the old allocs of destructive updates up front — the plan
        # applier frees that capacity for the replacements
        for m in missing:
            if m.stop_previous and m.previous is not None:
                self.plan.append_stopped_alloc(m.previous, m.stop_desc, "")

        # proposed live allocs by node: state minus plan stops.  With a
        # resident solver world the eager O(cluster) walk collapses to a
        # lazy per-node view — the solve reads usage from the
        # delta-maintained tensors, and the host fixups only ever touch
        # the chosen candidates' nodes
        if allocs_by_node is None:
            stopped_ids = {a.id for allocs in self.plan.node_update.values()
                           for a in allocs}
            if self.solver.resident_active(snapshot):
                allocs_by_node = LazyAllocsView(snapshot, stopped_ids)
            else:
                allocs_by_node = {}
                for n in nodes:
                    live = [a for a in snapshot.allocs_by_node(n.id)
                            if not a.terminal_status()
                            and a.id not in stopped_ids]
                    if live:
                        allocs_by_node[n.id] = live

        # sticky-disk placements prefer their previous node (reference:
        # generic_sched.go:628 findPreferredNode)
        if node_by_id is None:
            node_by_id = {n.id: n for n in nodes}
        batch_missing: List[_Missing] = []
        sticky_done: List[Tuple[_Missing, object, object]] = []
        for m in missing:
            pref = self._preferred_node(m, node_by_id)
            if pref is not None:
                placed = self._try_node(snapshot, pref, m, allocs_by_node)
                if placed is not None:
                    sticky_done.append((m, pref, placed))
                    continue
            batch_missing.append(m)
        for m, node, resources in sticky_done:
            self._emit_alloc(m, node, resources, score=0.0, metrics=None)

        if not batch_missing:
            return None

        # ---- group the remaining placements into per-tg asks ----
        by_tg: Dict[str, List[_Missing]] = {}
        for m in batch_missing:
            by_tg.setdefault(m.tg.name, []).append(m)

        # this job's proposed live allocs by node — the only slice the
        # anti-affinity / distinct / spread seeds ever read
        job_allocs = self._job_allocs_by_node(snapshot, allocs_by_node,
                                              node_by_id)
        proposed_by_job_tg: Dict[str, Dict[str, int]] = {}
        for nid, live in job_allocs.items():
            for a in live:
                proposed_by_job_tg.setdefault(
                    a.task_group, {}).setdefault(nid, 0)
                proposed_by_job_tg[a.task_group][nid] += 1

        asks: List[PlacementAsk] = []
        ask_missing: List[List[_Missing]] = []
        for tg_name, ms in by_tg.items():
            tg = ms[0].tg
            csi_err, csi_blocked = self._csi_state(snapshot, tg, nodes)
            if csi_err is not None:
                # a required CSI volume is missing or unclaimable: the
                # group cannot place anywhere (reference:
                # CSIVolumeChecker, feasible.go:194)
                from ..structs import AllocMetric
                metric = AllocMetric()
                metric.constraint_filtered = {csi_err: len(nodes)}
                metric.coalesced_failures = max(len(ms) - 1, 0)
                self.failed_tg_allocs[tg.name] = metric
                # a destructive update whose replacement cannot place
                # must keep its old alloc running: retract the eager
                # stops this group queued
                for m in ms:
                    if m.stop_previous and m.previous is not None:
                        lst = self.plan.node_update.get(
                            m.previous.node_id, [])
                        self.plan.node_update[m.previous.node_id] = [
                            a for a in lst if a.id != m.previous.id]
                        if not self.plan.node_update[m.previous.node_id]:
                            del self.plan.node_update[m.previous.node_id]
                continue
            penalty = frozenset(
                m.previous.node_id for m in ms
                if m.reschedule and m.previous is not None)
            existing = dict(proposed_by_job_tg.get(tg_name, {}))
            blocked, prop_limits = self._distinct_state(
                snapshot, tg, job_allocs, node_by_id)
            spread_seed = self._spread_seed(tg, job_allocs, node_by_id)
            asks.append(PlacementAsk(
                job=self.job, tg=tg, count=len(ms),
                penalty_nodes=penalty, existing_by_node=existing,
                distinct_hosts_blocked=blocked | csi_blocked,
                spread_seed=spread_seed,
                property_limits=prop_limits))
            ask_missing.append(ms)
        if not asks:
            return None
        return nodes, by_dc, allocs_by_node, asks, ask_missing

    def _csi_state(self, snapshot, tg, nodes):
        """CSI volume feasibility (reference: CSIVolumeChecker,
        feasible.go:194): every requested csi volume must exist, be
        schedulable, and have write capacity for writable requests;
        nodes not running the volume's plugin (healthy) are excluded
        from placement. Returns (fatal_reason | None, blocked_node_ids)."""
        vols = [(name, v) for name, v in tg.volumes.items()
                if v.type == "csi"]
        if not vols:
            return None, frozenset()
        blocked = set()
        for name, req in vols:
            vol = snapshot.csi_volume_by_id(self.job.namespace,
                                            req.source)
            if vol is None:
                return f"missing CSI volume {req.source}", frozenset()
            if not vol.schedulable:
                return f"CSI volume {req.source} unschedulable", \
                    frozenset()
            if not req.read_only and not vol.write_free():
                return (f"CSI volume {req.source} has exhausted its "
                        "write claims"), frozenset()
            for n in nodes:
                info = n.csi_node_plugins.get(vol.plugin_id)
                if info is None or not info.healthy:
                    blocked.add(n.id)
        return None, frozenset(blocked)

    def _consume_solve(self, snapshot, out, nodes, allocs_by_node,
                       missing: List[_Missing],
                       ask_missing: List[List[_Missing]],
                       span=None) -> None:
        """Post-solve work: emit allocs, preempt or record failures, and
        retract eager stops for failed destructive replacements. `out`
        placements must use ask indexes local to `ask_missing`.
        `span`: the eval's open solve trace span — ended here with the
        device counters (out.trace) and the per-placement corpus rows
        (chosen node + candidate score window + features, the learned-
        scorer training substrate)."""
        # map solver placements (contiguous per ask) back to missing
        from .preemption import preemption_enabled
        from ..utils.tracing import NULL_SPAN
        preempt_ok = preemption_enabled(
            snapshot.scheduler_config(), "batch" if self.batch else "service")
        # per-ask consume cursors instead of pop(0) list churn
        queues = [list(ms) for ms in ask_missing]
        cursor = [0] * len(queues)
        failed: set = set()
        # the per-placement corpus rows exist solely for the trace span:
        # skip building the nested dicts entirely when the span is not
        # sampled (the fused hot path at trace sample < 1) — at batch
        # 128 the row churn was a measurable slice of plan build
        want_rows = span is not None and span is not NULL_SPAN
        place_rows: List[dict] = []
        for placement in out.placements:
            g = placement.ask_index
            m = queues[g][cursor[g]]
            cursor[g] += 1
            if want_rows:
                place_rows.append(_placement_row(m, placement))
            if placement.node is None:
                if not (preempt_ok and self._try_preemption(
                        nodes, m, allocs_by_node)):
                    self._record_failure(m, placement)
                    failed.add(id(m))
                continue
            if placement.evicted:
                # the in-kernel preemption pass already selected this
                # placement's victim set (solver/kernel.py eviction
                # waves) — commit the (place, evict) pair without the
                # host-side walk
                self._commit_kernel_eviction(placement, m,
                                             allocs_by_node)
                continue
            self._emit_alloc(m, placement.node, placement.resources,
                             placement.score, placement.metrics)

        if self.failed_tg_allocs:
            # remember per-class eligibility for the blocked eval
            for elig in out.class_eligibility:
                self._class_eligibility.update(elig)
        self._stop_destructive_for_failed(missing, failed)
        if want_rows:
            span.set(**(getattr(out, "trace", None) or {}))
            span.end(placements=place_rows)

    def _stop_destructive_for_failed(self, missing: List[_Missing],
                                     failed: set) -> None:
        """A destructive update whose replacement failed to place must keep
        its old alloc running: retract the eager stop."""
        for m in missing:
            if not (m.stop_previous and m.previous is not None):
                continue
            if id(m) in failed:
                lst = self.plan.node_update.get(m.previous.node_id, [])
                self.plan.node_update[m.previous.node_id] = [
                    a for a in lst if a.id != m.previous.id]
                if not self.plan.node_update[m.previous.node_id]:
                    del self.plan.node_update[m.previous.node_id]

    def _commit_kernel_eviction(self, placement, m: _Missing,
                                allocs_by_node) -> None:
        """Commit a (place, evict) pair the device eviction pass
        selected: victims leave via plan.node_preemptions, the alloc
        lands with preempted_allocations set, and the shared
        allocs_by_node view advances so later placements (and the
        host-side fallback walk) see both sides."""
        from ..utils.metrics import global_metrics as _m
        _m.incr_counter("scheduler.preempt.kernel")
        node = placement.node
        vset = set(placement.evicted)
        proposed = list(allocs_by_node.get(node.id, ())) \
            if allocs_by_node is not None else []
        victims = [a for a in proposed if a.id in vset]
        alloc = self._emit_alloc(m, node, placement.resources,
                                 placement.score, placement.metrics)
        alloc.preempted_allocations = sorted(vset)
        if allocs_by_node is not None:
            allocs_by_node[node.id] = [a for a in proposed
                                       if a.id not in vset] + [alloc]
        for v in victims:
            self.plan.append_preempted_alloc(v, alloc.id)

    def _try_preemption(self, nodes, m: _Missing, allocs_by_node) -> bool:
        """Second pass for an exhausted placement: across ALL feasible
        nodes, find victim sets (task-group resources, then network and
        device dimensions — preemption.find_preemption) and place on the
        BEST node — highest bin-pack score after eviction, matching the
        reference where preemption options feed the regular rank/max
        pipeline (preemption.go wired via rank.go BinPackIterator) —
        not the first node that works.  Counted as the host-side
        FALLBACK — ISSUE 7 steady state should select evictions
        in-kernel instead (scheduler.preempt.kernel)."""
        from ..structs.funcs import score_fit, allocs_fit
        from ..utils.metrics import global_metrics as _m
        from .preemption import find_preemption
        _m.incr_counter("scheduler.preempt.host_fallback")

        best = None                # (score, node, victims, resources)
        for node in nodes:
            ok, _why = hostfeas.group_feasible(node, self.job, m.tg)
            if not ok:
                continue
            proposed = allocs_by_node.get(node.id, [])
            victims = find_preemption(node, proposed, self.job, m.tg)
            if not victims:
                continue
            victim_ids = {v.id for v in victims}
            remaining = [a for a in proposed if a.id not in victim_ids]
            trial = dict(allocs_by_node)
            trial[node.id] = remaining
            resources = self.solver._host_commit(
                node, 0, PlacementAsk(job=self.job, tg=m.tg, count=1),
                {}, {}, trial)
            if resources is None:
                continue
            probe = Allocation(id="probe", task_group=m.tg.name,
                               allocated_resources=resources)
            fit, _dim, used = allocs_fit(node, remaining + [probe])
            if not fit:
                continue
            score = score_fit(node, used)
            if best is None or score > best[0]:
                best = (score, node, victims, resources)
        if best is None:
            return False
        _score, node, victims, resources = best
        victim_ids = {v.id for v in victims}
        remaining = [a for a in allocs_by_node.get(node.id, [])
                     if a.id not in victim_ids]
        alloc = self._emit_alloc(m, node, resources, _score, None)
        alloc.preempted_allocations = sorted(victim_ids)
        # later placements must see both the evictions and the new
        # alloc's usage
        allocs_by_node[node.id] = remaining + [alloc]
        for v in victims:
            self.plan.append_preempted_alloc(v, alloc.id)
        return True

    def _preferred_node(self, m: _Missing, node_by_id):
        if m.previous is None or not m.tg.ephemeral_disk.sticky:
            return None
        return node_by_id.get(m.previous.node_id)

    def _try_node(self, snapshot, node, m: _Missing, allocs_by_node):
        """Host-side single-node feasibility + commit for sticky placements."""
        ok, _reason = hostfeas.group_feasible(node, self.job, m.tg)
        if not ok:
            return None
        resources = self.solver._host_commit(
            node, 0, PlacementAsk(job=self.job, tg=m.tg, count=1),
            {}, {}, allocs_by_node)
        if resources is None:
            return None
        from ..structs.funcs import allocs_fit
        live = list(allocs_by_node.get(node.id, []))
        probe = Allocation(id=generate_uuid(), job=self.job,
                           job_id=self.job.id, node_id=node.id,
                           allocated_resources=resources,
                           task_group=m.tg.name)
        fit, _dim, _used = allocs_fit(node, live + [probe])
        if not fit:
            return None
        allocs_by_node.setdefault(node.id, []).append(probe)
        # tracked separately: the solver's resident world overlays probe
        # usage onto its delta-maintained tensors instead of re-walking
        # allocs_by_node
        self._sticky_probes.append(probe)
        return resources

    def _job_allocs_by_node(self, snapshot, allocs_by_node, node_by_id
                            ) -> Dict[str, List[Allocation]]:
        """This job's proposed live allocs grouped by node — equal to
        filtering allocs_by_node down to job_id, but O(job) via the job
        index (plus the tracked sticky probes) when the view is lazy,
        so the seed walks never materialize the cluster."""
        out: Dict[str, List[Allocation]] = {}
        if isinstance(allocs_by_node, LazyAllocsView):
            for a in snapshot.allocs_by_job(self.job.namespace,
                                            self.job.id):
                if (a.terminal_status() or a.id in allocs_by_node.excluded
                        or a.node_id not in node_by_id):
                    continue
                out.setdefault(a.node_id, []).append(a)
            for p in self._sticky_probes:
                out.setdefault(p.node_id, []).append(p)
            return out
        for nid, live in allocs_by_node.items():
            lst = [a for a in live if a.job_id == self.job.id]
            if lst:
                out[nid] = lst
        return out

    def _distinct_state(self, snapshot, tg: TaskGroup, job_allocs,
                        node_by_id):
        """Existing-state inputs for distinct_hosts / distinct_property.
        `job_allocs` is this job's proposed live allocs by node
        (_job_allocs_by_node)."""
        blocked = set()
        merged = hostfeas.merged_constraints(self.job, tg)
        has_job_distinct = any(
            c.operand == "distinct_hosts" for c in self.job.constraints)
        has_distinct = has_job_distinct or any(
            c.operand == "distinct_hosts" for c in merged)
        if has_distinct:
            for nid, live in job_allocs.items():
                for a in live:
                    if has_job_distinct or a.task_group == tg.name:
                        blocked.add(nid)
                        break
        # distinct_property limits, keyed by (scope, target) so job-level
        # charges are shared across the job's asks in one batch while
        # tg-level ones count only that group's allocs
        prop_limits: Dict[Tuple[str, str], Tuple[int, Dict[str, int]]] = {}

        def add_prop(c, job_scope: bool) -> None:
            limit = 1
            if c.rtarget:
                try:
                    limit = int(c.rtarget)
                except ValueError:
                    limit = 1
            counts: Dict[str, int] = {}
            for nid, live in job_allocs.items():
                n_cnt = sum(
                    1 for a in live
                    if job_scope or a.task_group == tg.name)
                if not n_cnt:
                    continue
                node = node_by_id.get(nid)
                if node is None:
                    continue
                val, ok = resolve_node_target(node, c.ltarget)
                if ok:
                    counts[str(val)] = counts.get(str(val), 0) + n_cnt
            # include the job id: the fused fleet solve mixes asks from
            # multiple jobs in one Solver.solve() with a shared prop_used
            # map, so scope keys must not collide across jobs
            ns = self.job.namespace
            key = (f"job:{ns}:{self.job.id}" if job_scope
                   else f"tg:{ns}:{self.job.id}:{tg.name}", c.ltarget)
            prop_limits[key] = (limit, counts)

        for c in self.job.constraints:
            if c.operand == CONSTRAINT_DISTINCT_PROPERTY:
                add_prop(c, True)
        tg_cons = list(tg.constraints)
        for t in tg.tasks:
            tg_cons.extend(t.constraints)
        for c in tg_cons:
            if c.operand == CONSTRAINT_DISTINCT_PROPERTY:
                add_prop(c, False)
        return frozenset(blocked), prop_limits

    def _spread_seed(self, tg: TaskGroup, job_allocs, node_by_id):
        seed: Dict[str, Dict[str, int]] = {}
        spreads = list(self.job.spreads) + list(tg.spreads)
        if not spreads:
            return seed
        for sp in spreads:
            counts: Dict[str, int] = {}
            for nid, live in job_allocs.items():
                n_tg = sum(1 for a in live
                           if a.task_group == tg.name)
                if not n_tg:
                    continue
                node = node_by_id.get(nid)
                if node is None:
                    continue
                val, ok = resolve_node_target(node, sp.attribute)
                if ok:
                    counts[str(val)] = counts.get(str(val), 0) + n_tg
            seed[sp.attribute] = counts
        return seed

    # ------------------------------------------------------------- results
    def _emit_alloc(self, m: _Missing, node, resources, score: float,
                    metrics) -> Allocation:
        from ..structs import AllocMetric
        now = _time.time()
        alloc = Allocation(
            id=generate_uuid(), namespace=self.eval.namespace,
            eval_id=self.eval.id, name=m.name, job_id=self.job.id,
            job=self.job, task_group=m.tg.name, node_id=node.id,
            node_name=node.name,
            allocated_resources=resources,
            metrics=metrics or AllocMetric(),
            desired_status=ALLOC_DESIRED_RUN,
            client_status=ALLOC_CLIENT_PENDING,
            deployment_id=self.deployment.id if self.deployment else "",
            create_time=now, modify_time=now)
        if metrics is not None:
            metrics.scores = {node.id: score}
        if m.previous is not None:
            alloc.previous_allocation = m.previous.id
            if m.reschedule:
                _update_reschedule_tracker(alloc, m.previous, now)
        if m.canary and self.deployment is not None:
            alloc.deployment_status = AllocDeploymentStatus(canary=True)
        self.plan.append_alloc(alloc)
        return alloc

    def _record_failure(self, m: _Missing, placement) -> None:
        from ..structs import AllocMetric
        existing = self.failed_tg_allocs.get(m.tg.name)
        if existing is not None:
            existing.coalesced_failures += 1
            return
        metric = placement.metrics if placement is not None else AllocMetric()
        self.failed_tg_allocs[m.tg.name] = metric

    def _create_blocked_eval(self, planning_failure: bool) -> None:
        escaped = self._escaped or not self._class_eligibility
        blocked = self.eval.create_blocked_eval(
            self._class_eligibility, escaped, "")
        # the scheduling snapshot's index, so BlockedEvals can detect
        # capacity changes that raced this eval (missed-unblock check)
        blocked.snapshot_index = getattr(self.snapshot, "index", 0)
        if planning_failure:
            blocked.triggered_by = EVAL_TRIGGER_MAX_PLANS
            blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
        else:
            blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS_DESC
        self.planner.create_eval(blocked)
        self.blocked = blocked

    def _set_status(self, status: str, description: str) -> None:
        ev = copy.copy(self.eval)
        ev.status = status
        ev.status_description = description
        if self.blocked is not None:
            ev.blocked_eval = self.blocked.id
        ev.failed_tg_allocs = dict(self.failed_tg_allocs)
        ev.queued_allocations = dict(self.queued_allocs)
        if self.deployment is not None and status == EVAL_STATUS_COMPLETE:
            ev.deployment_id = self.deployment.id
        self.planner.update_eval(ev)


def _placement_row(m: _Missing, placement) -> dict:
    """One trace-corpus row per placement decision: the chosen (group,
    node, score) plus the candidate score window and the per-eval
    feasibility features — failed placements ride along with node_id ""
    and the failure cause (negative training examples)."""
    metrics = placement.metrics
    row = {
        "group": m.tg.name,
        "node_id": placement.node.id if placement.node is not None
        else "",
        "score": round(float(placement.score), 6),
        "candidates": [
            {"node_id": c.get("node_id", ""),
             "score": round(float(c.get("normalized_score", 0.0)), 6)}
            for c in (metrics.score_meta or [])]
        if metrics is not None else [],
        "features": {
            "nodes_evaluated": metrics.nodes_evaluated,
            "nodes_filtered": metrics.nodes_filtered,
            "nodes_exhausted": metrics.nodes_exhausted,
            "dimension_exhausted": dict(metrics.dimension_exhausted),
            "constraint_filtered": dict(metrics.constraint_filtered),
        } if metrics is not None else {},
    }
    if placement.evicted:
        row["evicted"] = list(placement.evicted)
    if placement.failed_reason:
        row["failed_reason"] = placement.failed_reason
    return row


def _update_reschedule_tracker(alloc: Allocation, prev: Allocation,
                               now: float) -> None:
    """Carry the reschedule history onto the replacement (reference:
    generic_sched.go:591 updateRescheduleTracker — keeps events within the
    policy interval, appends this reschedule)."""
    policy = None
    if prev.job is not None:
        tg = prev.job.lookup_task_group(prev.task_group)
        if tg is not None:
            policy = tg.reschedule_policy
    events: List[RescheduleEvent] = []
    if prev.reschedule_tracker:
        if policy is not None and not policy.unlimited and policy.interval_s:
            window = now - policy.interval_s
            events = [e for e in prev.reschedule_tracker.events
                      if e.reschedule_time > window]
        else:
            events = list(prev.reschedule_tracker.events)
    delay = prev.next_delay(policy) if policy is not None else 0.0
    events.append(RescheduleEvent(
        reschedule_time=now, prev_alloc_id=prev.id,
        prev_node_id=prev.node_id, delay_s=delay))
    alloc.reschedule_tracker = RescheduleTracker(events=events)
