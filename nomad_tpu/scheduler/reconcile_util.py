"""Set algebra over allocations + alloc-name index reuse.

Pure host code — the reconciler's primitives. Reference semantics:
scheduler/reconcile_util.go (allocSet ops :113-195, filterByTainted :197,
filterByRescheduleable :237, allocNameIndex :384, bitmapFrom :396).
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..structs import (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED,
                       ALLOC_CLIENT_LOST, ALLOC_DESIRED_EVICT,
                       ALLOC_DESIRED_STOP, JOB_TYPE_BATCH, Allocation,
                       Deployment, Node, alloc_name)
from ..utils.bitmap import Bitmap

# An alloc within this window of its reschedule time is rescheduled now
# rather than via a delayed follow-up eval.
RESCHEDULE_WINDOW_S = 1.0

AllocSet = Dict[str, Allocation]


def alloc_set(allocs: Iterable[Allocation]) -> AllocSet:
    return {a.id: a for a in allocs}


def union(*sets: AllocSet) -> AllocSet:
    out: AllocSet = {}
    for s in sets:
        out.update(s)
    return out


def difference(base: AllocSet, *others: AllocSet) -> AllocSet:
    removed: Set[str] = set()
    for s in others:
        removed.update(s.keys())
    return {k: v for k, v in base.items() if k not in removed}


def from_keys(base: AllocSet, keys: Iterable[str]) -> AllocSet:
    return {k: base[k] for k in keys if k in base}


def name_order(s: AllocSet) -> List[Allocation]:
    """Deterministic iteration: by name then id."""
    return sorted(s.values(), key=lambda a: (a.name, a.id))


def name_set(s: AllocSet) -> Set[str]:
    return {a.name for a in s.values()}


def filter_by_deployment(s: AllocSet, deployment_id: str
                         ) -> Tuple[AllocSet, AllocSet]:
    """Returns (part_of, not_part_of)."""
    match, rest = {}, {}
    for k, a in s.items():
        (match if a.deployment_id == deployment_id else rest)[k] = a
    return match, rest


def filter_non_terminal(s: AllocSet) -> AllocSet:
    return {k: a for k, a in s.items() if not a.terminal_status()}


def filter_by_tainted(s: AllocSet, tainted: Dict[str, Optional[Node]]
                      ) -> Tuple[AllocSet, AllocSet, AllocSet]:
    """Split into (untainted, migrate, lost) given the tainted-node map
    (node_id -> Node or None for deregistered nodes)."""
    untainted: AllocSet = {}
    migrate: AllocSet = {}
    lost: AllocSet = {}
    for k, a in s.items():
        # terminal allocs never migrate
        if a.terminal_status():
            untainted[k] = a
            continue
        # drainer marks allocs for migration explicitly
        if a.desired_transition.should_migrate():
            migrate[k] = a
            continue
        if a.node_id not in tainted:
            untainted[k] = a
            continue
        n = tainted[a.node_id]
        if n is None or n.terminal_status():
            lost[k] = a
        else:
            untainted[k] = a
    return untainted, migrate, lost


def _should_filter(a: Allocation, is_batch: bool) -> Tuple[bool, bool]:
    """Returns (untainted, ignore): whether the alloc should be kept as-is
    or dropped from consideration, before reschedule classification."""
    if is_batch:
        # batch: a stopped alloc that finished its work stays accounted for;
        # one that was stopped mid-run is simply gone.
        if a.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            if a.ran_successfully():
                return True, False
            return False, True
        if a.client_status != ALLOC_CLIENT_FAILED:
            return True, False
        return False, False
    # service/system
    if a.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
        return False, True
    if a.client_status in (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_LOST):
        return False, True
    return False, False


def _update_by_reschedulable(a: Allocation, now: float, eval_id: str,
                             deployment: Optional[Deployment]
                             ) -> Tuple[bool, bool, float]:
    """Returns (reschedule_now, reschedule_later, reschedule_time)."""
    # during an active deployment only explicitly-marked allocs reschedule
    if (deployment is not None and a.deployment_id == deployment.id
            and deployment.active()
            and not (a.desired_transition.reschedule is True)):
        return False, False, 0.0
    if a.desired_transition.should_force_reschedule():
        return True, False, 0.0
    policy = None
    if a.job is not None:
        tg = a.job.lookup_task_group(a.task_group)
        if tg is not None:
            policy = tg.reschedule_policy
    resched_time, eligible = a.next_reschedule_time(policy)
    if eligible and (a.follow_up_eval_id == eval_id
                     or resched_time - now <= RESCHEDULE_WINDOW_S):
        return True, False, resched_time
    if eligible and not a.follow_up_eval_id:
        return False, True, resched_time
    return False, False, 0.0


def filter_by_rescheduleable(s: AllocSet, is_batch: bool, now: float,
                             eval_id: str,
                             deployment: Optional[Deployment]
                             ) -> Tuple[AllocSet, AllocSet,
                                        List[Tuple[Allocation, float]]]:
    """Split into (untainted, reschedule_now, reschedule_later) where
    reschedule_later entries carry their eligible reschedule time."""
    untainted: AllocSet = {}
    resched_now: AllocSet = {}
    resched_later: List[Tuple[Allocation, float]] = []
    for k, a in s.items():
        # already replaced by a newer allocation
        if a.next_allocation:
            continue
        if not is_batch and a.server_terminal_status():
            continue
        is_untainted, ignore = _should_filter(a, is_batch)
        if is_untainted:
            untainted[k] = a
        if is_untainted or ignore:
            continue
        now_ok, later_ok, when = _update_by_reschedulable(
            a, now, eval_id, deployment)
        if now_ok:
            resched_now[k] = a
        elif later_ok:
            # stays in place (still running its restart policy out) but a
            # follow-up eval is scheduled for it
            untainted[k] = a
            resched_later.append((a, when))
        else:
            untainted[k] = a
    return untainted, resched_now, resched_later


def bitmap_from(s: AllocSet, min_size: int) -> Bitmap:
    """Bitmap of name indexes in use (reference: bitmapFrom :396)."""
    size = min_size
    for a in s.values():
        idx = a.index()
        if idx + 1 > size:
            size = idx + 1
    if size == 0:
        size = 8
    b = Bitmap(size)
    for a in s.values():
        idx = a.index()
        if idx >= 0:
            b.set(idx)
    return b


class AllocNameIndex:
    """Tracks which `job.group[i]` names are in use so replacements reuse
    the lowest free indexes (reference: allocNameIndex :384)."""

    def __init__(self, job_id: str, task_group: str, count: int,
                 in_use: AllocSet):
        self.job_id = job_id
        self.task_group = task_group
        self.count = count
        self.b = bitmap_from(in_use, count)
        self._duplicates: Dict[int, int] = {}
        seen: Set[int] = set()
        for a in in_use.values():
            idx = a.index()
            if idx >= 0:
                if idx in seen:
                    self._duplicates[idx] = self._duplicates.get(idx, 0) + 1
                seen.add(idx)

    def _name(self, idx: int) -> str:
        return alloc_name(self.job_id, self.task_group, idx)

    def set_index(self, idx: int) -> None:
        if 0 <= idx < self.b.size:
            self.b.set(idx)

    def unset_index(self, idx: int) -> None:
        if 0 <= idx < self.b.size:
            if self._duplicates.get(idx):
                self._duplicates[idx] -= 1
                if self._duplicates[idx] == 0:
                    del self._duplicates[idx]
            else:
                self.b.unset(idx)

    def highest(self, n: int) -> Set[str]:
        """Names of the n highest set indexes (candidates for removal on
        scale-down)."""
        out: Set[str] = set()
        for idx in reversed(self.b.indexes_in_range(True, 0, self.b.size - 1)):
            out.add(self._name(idx))
            if len(out) == n:
                break
        return out

    def next(self, n: int) -> List[str]:
        """The next n unused names, lowest index first."""
        out: List[str] = []
        for idx in self.b.indexes_in_range(False, 0, self.count - 1):
            out.append(self._name(idx))
            self.b.set(idx)
            if len(out) == n:
                return out
        # overflow past count (e.g. canary overlap): continue upward
        idx = self.count
        while len(out) < n:
            if idx >= self.b.size or not self.b.check(idx):
                out.append(self._name(idx))
                if idx < self.b.size:
                    self.b.set(idx)
            idx += 1
        return out

    def next_canaries(self, n: int, existing: AllocSet,
                      destructive: AllocSet) -> List[str]:
        """Pick canary names: prefer indexes of allocs being destructively
        replaced (their names free up), then unset indexes, then overflow."""
        out: List[str] = []
        existing_names = name_set(existing)
        dmap = bitmap_from(destructive, self.count)
        for idx in dmap.indexes_in_range(True, 0, self.count - 1):
            name = self._name(idx)
            if name not in existing_names:
                out.append(name)
                self.set_index(idx)
                if len(out) == n:
                    return out
        for idx in self.b.indexes_in_range(False, 0, self.count - 1):
            name = self._name(idx)
            if name not in existing_names:
                out.append(name)
                self.set_index(idx)
                if len(out) == n:
                    return out
        idx = self.count
        while len(out) < n:
            name = self._name(idx)
            if name not in existing_names and (
                    idx >= self.b.size or not self.b.check(idx)):
                out.append(name)
                self.set_index(idx)
            idx += 1
        return out
