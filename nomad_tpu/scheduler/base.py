"""Scheduler registry (reference: scheduler/scheduler.go:23
BuiltinSchedulers + NewScheduler factory)."""
from __future__ import annotations

from ..structs import JOB_TYPE_BATCH, JOB_TYPE_SERVICE, JOB_TYPE_SYSTEM


def new_scheduler(sched_type: str, state, planner, solver=None):
    """`solver`: the worker's long-lived Solver — sharing it across
    evals is what keeps its resident cluster world (and tensorizer
    memoization) warm between invocations."""
    from .generic import GenericScheduler
    from .system import SystemScheduler
    if sched_type == JOB_TYPE_SERVICE:
        return GenericScheduler(state, planner, batch=False,
                                solver=solver)
    if sched_type == JOB_TYPE_BATCH:
        return GenericScheduler(state, planner, batch=True,
                                solver=solver)
    if sched_type == JOB_TYPE_SYSTEM:
        return SystemScheduler(state, planner, solver=solver)
    raise ValueError(f"unknown scheduler type {sched_type!r}")
