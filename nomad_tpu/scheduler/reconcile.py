"""The allocation reconciler: pure diff of job spec vs cluster state.

Given (job, existing allocs, tainted nodes, active deployment) produce the
sets {place, stop, inplace, destructive, migrate} plus deployment
creation/updates and delayed-reschedule follow-up evals. No I/O, no device
code — this is the behavior-dense heart of service/batch scheduling.

Reference semantics: scheduler/reconcile.go (`allocReconciler` :39,
`Compute` :184, `computeGroup` :306, canary handling :566, `computeLimit`
:618, `computePlacements` :662, `computeStop` :699, `computeUpdates` :810,
delayed-reschedule batching :833).
"""
from __future__ import annotations

import math
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..structs import (ALLOC_CLIENT_LOST, ALLOC_LOST,
                       ALLOC_MIGRATING, ALLOC_NOT_NEEDED, ALLOC_RESCHEDULED,
                       ALLOC_UPDATING,
                       DEPLOYMENT_DESC_AUTO_PROMOTION,
                       DEPLOYMENT_DESC_NEEDS_PROMOTION,
                       DEPLOYMENT_DESC_NEWER_JOB, DEPLOYMENT_DESC_STOPPED_JOB,
                       DEPLOYMENT_STATUS_CANCELLED,
                       DEPLOYMENT_STATUS_FAILED, DEPLOYMENT_STATUS_PAUSED,
                       DEPLOYMENT_STATUS_SUCCESSFUL,
                       DEPLOYMENT_DESC_SUCCESSFUL,
                       EVAL_STATUS_PENDING, EVAL_TRIGGER_FAILED_FOLLOW_UP,
                       Allocation, Deployment, DeploymentState,
                       DeploymentStatusUpdate, Evaluation, Job, Node,
                       TaskGroup)
from . import reconcile_util as rutil
from .reconcile_util import AllocSet

# Follow-up evals for delayed reschedules within this window share one eval.
BATCHED_FAILED_ALLOC_WINDOW_S = 5.0


@dataclass
class AllocPlaceResult:
    name: str
    task_group: TaskGroup
    previous_alloc: Optional[Allocation] = None
    reschedule: bool = False
    canary: bool = False


@dataclass
class AllocDestructiveResult:
    place_name: str
    place_task_group: TaskGroup
    stop_alloc: Allocation
    stop_status_description: str


@dataclass
class AllocStopResult:
    alloc: Allocation
    client_status: str = ""
    status_description: str = ""


@dataclass
class DesiredUpdates:
    """Per-task-group change accounting (surfaced by `plan` dry runs)."""
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0


@dataclass
class ReconcileResults:
    place: List[AllocPlaceResult] = field(default_factory=list)
    destructive_update: List[AllocDestructiveResult] = field(default_factory=list)
    inplace_update: List[Allocation] = field(default_factory=list)
    stop: List[AllocStopResult] = field(default_factory=list)
    attribute_updates: Dict[str, Allocation] = field(default_factory=dict)
    deployment: Optional[Deployment] = None           # newly created
    deployment_updates: List[DeploymentStatusUpdate] = field(default_factory=list)
    desired_tg_updates: Dict[str, DesiredUpdates] = field(default_factory=dict)
    desired_followup_evals: Dict[str, List[Evaluation]] = field(default_factory=dict)

    def changes(self) -> int:
        return (len(self.place) + len(self.inplace_update)
                + len(self.destructive_update) + len(self.stop))


# (existing alloc, new job, new tg) -> (ignore, destructive, inplace alloc)
AllocUpdateFn = Callable[[Allocation, Job, TaskGroup],
                         Tuple[bool, bool, Optional[Allocation]]]


class Reconciler:
    def __init__(self, alloc_update_fn: AllocUpdateFn, batch: bool,
                 job_id: str, job: Optional[Job],
                 deployment: Optional[Deployment],
                 existing_allocs: List[Allocation],
                 tainted_nodes: Dict[str, Optional[Node]],
                 eval_id: str, now: Optional[float] = None):
        self.alloc_update_fn = alloc_update_fn
        self.batch = batch
        self.job_id = job_id
        self.job = job
        self.deployment = deployment.copy() if deployment else None
        self.old_deployment: Optional[Deployment] = None
        self.existing_allocs = existing_allocs
        self.tainted_nodes = tainted_nodes
        self.eval_id = eval_id
        self.now = now if now is not None else _time.time()
        self.deployment_paused = False
        self.deployment_failed = False
        self.result = ReconcileResults()

    # ------------------------------------------------------------------ API
    def compute(self) -> ReconcileResults:
        matrix: Dict[str, AllocSet] = {}
        for a in self.existing_allocs:
            matrix.setdefault(a.task_group, {})[a.id] = a
        # groups in the job with no existing allocs still need placements
        if self.job is not None and not self.job.stopped():
            for tg in self.job.task_groups:
                matrix.setdefault(tg.name, {})

        self._cancel_deployments()

        if self.job is None or self.job.stopped():
            self._handle_stop(matrix)
            return self.result

        if self.deployment is not None:
            self.deployment_paused = (
                self.deployment.status == DEPLOYMENT_STATUS_PAUSED)
            self.deployment_failed = (
                self.deployment.status == DEPLOYMENT_STATUS_FAILED)

        complete = True
        for group, allocs in matrix.items():
            complete &= self._compute_group(group, allocs)

        # a finished deployment flips to successful
        if self.deployment is not None and complete:
            self.result.deployment_updates.append(DeploymentStatusUpdate(
                deployment_id=self.deployment.id,
                status=DEPLOYMENT_STATUS_SUCCESSFUL,
                status_description=DEPLOYMENT_DESC_SUCCESSFUL))

        # a created deployment advertises whether it awaits promotion
        d = self.result.deployment
        if d is not None and d.requires_promotion():
            d.status_description = (DEPLOYMENT_DESC_AUTO_PROMOTION
                                    if d.has_auto_promote()
                                    else DEPLOYMENT_DESC_NEEDS_PROMOTION)
        return self.result

    # ------------------------------------------------------- deployment mgmt
    def _cancel_deployments(self) -> None:
        if self.deployment is None:
            return
        d = self.deployment
        stopped = self.job is None or self.job.stopped()
        if stopped:
            if d.active():
                self.result.deployment_updates.append(DeploymentStatusUpdate(
                    deployment_id=d.id, status=DEPLOYMENT_STATUS_CANCELLED,
                    status_description=DEPLOYMENT_DESC_STOPPED_JOB))
            self.old_deployment = d
            self.deployment = None
            return
        # deployment for an older version of the job: cancel it
        if self.job is not None and (
                d.job_create_index != self.job.create_index
                or d.job_version != self.job.version):
            if d.active():
                self.result.deployment_updates.append(DeploymentStatusUpdate(
                    deployment_id=d.id, status=DEPLOYMENT_STATUS_CANCELLED,
                    status_description=DEPLOYMENT_DESC_NEWER_JOB))
            self.old_deployment = d
            self.deployment = None
            return
        # a finished-successful deployment is history; failed/cancelled ones
        # stay current so they keep gating placements
        if d.status == DEPLOYMENT_STATUS_SUCCESSFUL:
            self.old_deployment = d
            self.deployment = None

    # ---------------------------------------------------------- stopped job
    def _handle_stop(self, matrix: Dict[str, AllocSet]) -> None:
        for group, allocs in matrix.items():
            du = self.result.desired_tg_updates.setdefault(
                group, DesiredUpdates())
            remaining = rutil.filter_non_terminal(allocs)
            untainted, migrate, lost = rutil.filter_by_tainted(
                remaining, self.tainted_nodes)
            du.stop += len(remaining)
            self._mark_stop(untainted, "", ALLOC_NOT_NEEDED)
            self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)

    def _mark_stop(self, allocs: AllocSet, client_status: str,
                   desc: str) -> None:
        for a in rutil.name_order(allocs):
            self.result.stop.append(AllocStopResult(
                alloc=a, client_status=client_status,
                status_description=desc))

    # ------------------------------------------------------------ per group
    def _compute_group(self, group: str, all_allocs: AllocSet) -> bool:
        du = self.result.desired_tg_updates.setdefault(group, DesiredUpdates())
        tg = self.job.lookup_task_group(group)

        # group removed from the job: stop everything
        if tg is None:
            untainted, migrate, lost = rutil.filter_by_tainted(
                all_allocs, self.tainted_nodes)
            remaining = rutil.filter_non_terminal(untainted)
            self._mark_stop(remaining, "", ALLOC_NOT_NEEDED)
            self._mark_stop(migrate, "", ALLOC_NOT_NEEDED)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)
            du.stop += len(remaining) + len(migrate) + len(lost)
            return True

        # deployment state for this group
        existing_deployment = False
        dstate: Optional[DeploymentState] = None
        if self.deployment is not None:
            dstate = self.deployment.task_groups.get(group)
            existing_deployment = dstate is not None
        if dstate is None:
            dstate = DeploymentState()
            if tg.update is not None:
                dstate.auto_revert = tg.update.auto_revert
                dstate.auto_promote = tg.update.auto_promote
                dstate.progress_deadline_s = tg.update.progress_deadline_s

        all_allocs, old_ignore = self._filter_old_terminal(all_allocs)
        du.ignore += len(old_ignore)

        canaries, all_allocs = self._handle_group_canaries(all_allocs, du)

        untainted, migrate, lost = rutil.filter_by_tainted(
            all_allocs, self.tainted_nodes)

        untainted, resched_now, resched_later = rutil.filter_by_rescheduleable(
            untainted, self.batch, self.now, self.eval_id, self.deployment)

        self._handle_delayed_reschedules(resched_later, all_allocs, group)

        name_index = rutil.AllocNameIndex(
            self.job_id, group, tg.count,
            rutil.union(untainted, migrate, resched_now))

        canary_state = (dstate is not None and dstate.desired_canaries != 0
                        and not dstate.promoted)
        stop = self._compute_stop(tg, name_index, untainted, migrate, lost,
                                  canaries, canary_state)
        du.stop += len(stop)
        untainted = rutil.difference(untainted, stop)

        ignore, inplace, destructive = self._compute_updates(tg, untainted)
        du.ignore += len(ignore)
        du.in_place_update += len(inplace)
        if not existing_deployment:
            dstate.desired_total += len(destructive) + len(inplace)

        if canary_state:
            untainted = rutil.difference(untainted, canaries)

        # create canaries when a destructive change needs them
        strategy = tg.update
        canaries_promoted = dstate is not None and dstate.promoted
        require_canary = (len(destructive) != 0 and strategy is not None
                          and len(canaries) < strategy.canary
                          and not canaries_promoted)
        if (require_canary and not self.deployment_paused
                and not self.deployment_failed):
            number = strategy.canary - len(canaries)
            du.canary += number
            if not existing_deployment:
                dstate.desired_canaries = strategy.canary
            for name in name_index.next_canaries(number, canaries,
                                                 destructive):
                self.result.place.append(AllocPlaceResult(
                    name=name, task_group=tg, canary=True))

        canary_state = (dstate is not None and dstate.desired_canaries != 0
                        and not dstate.promoted)
        limit = self._compute_limit(tg, untainted, destructive, migrate,
                                    canary_state)

        place = self._compute_placements(tg, name_index, untainted, migrate,
                                         resched_now)
        if not existing_deployment:
            dstate.desired_total += len(place)

        place_ready = (not self.deployment_paused
                       and not self.deployment_failed and not canary_state)
        if place_ready:
            du.place += len(place)
            self.result.place.extend(place)
            # the failed allocs being replaced right now are stopped
            self._mark_stop(resched_now, "", ALLOC_RESCHEDULED)
            du.stop += len(resched_now)
            # placements consume the rolling-update budget first
            limit -= min(len(place), limit)
        else:
            # even a gated deployment replaces lost capacity and failed
            # allocs (unless the failure is part of the failed deployment)
            if lost:
                allowed = min(len(lost), len(place))
                du.place += allowed
                self.result.place.extend(place[:allowed])
            if resched_now:
                for p in place:
                    prev = p.previous_alloc
                    if not p.reschedule:
                        continue
                    if (self.deployment_failed and prev is not None
                            and self.deployment is not None
                            and prev.deployment_id == self.deployment.id):
                        continue
                    self.result.place.append(p)
                    du.place += 1
                    self.result.stop.append(AllocStopResult(
                        alloc=prev, status_description=ALLOC_RESCHEDULED))
                    du.stop += 1

        if place_ready:
            n = min(len(destructive), limit)
            du.destructive_update += n
            du.ignore += len(destructive) - n
            for a in rutil.name_order(destructive)[:n]:
                self.result.destructive_update.append(AllocDestructiveResult(
                    place_name=a.name, place_task_group=tg, stop_alloc=a,
                    stop_status_description=ALLOC_UPDATING))
        else:
            du.ignore += len(destructive)

        # migrations always happen: stop on the old node, place on a new one
        du.migrate += len(migrate)
        for a in rutil.name_order(migrate):
            self.result.stop.append(AllocStopResult(
                alloc=a, status_description=ALLOC_MIGRATING))
            self.result.place.append(AllocPlaceResult(
                name=a.name, task_group=tg, previous_alloc=a))

        # create a deployment only on first run or a spec change — not for
        # routine reschedules/lost replacements of the current version
        updating_spec = bool(destructive) or bool(self.result.inplace_update)
        had_running = any(
            a.job is not None and a.job.version == self.job.version
            and a.job.create_index == self.job.create_index
            for a in all_allocs.values())
        if (not existing_deployment and strategy is not None
                and strategy.rolling() and dstate.desired_total != 0
                and (not had_running or updating_spec)
                and not self.job.is_batch()):
            if self.deployment is None:
                self.deployment = Deployment(
                    namespace=self.job.namespace, job_id=self.job.id,
                    job_version=self.job.version,
                    job_modify_index=self.job.modify_index,
                    job_create_index=self.job.create_index)
                self.result.deployment = self.deployment
            self.deployment.task_groups[group] = dstate

        deployment_complete = (
            not destructive and not inplace and not place and not migrate
            and not resched_now and not resched_later and not require_canary)
        # and every deployment alloc must be healthy (auto-revert depends on
        # the deployment staying non-successful until then)
        if deployment_complete and self.deployment is not None:
            ds = self.deployment.task_groups.get(group)
            if ds is not None:
                if (ds.healthy_allocs < max(ds.desired_total,
                                            ds.desired_canaries)
                        or (ds.desired_canaries > 0 and not ds.promoted)):
                    deployment_complete = False
        return deployment_complete

    # ------------------------------------------------------------- helpers
    def _filter_old_terminal(self, s: AllocSet) -> Tuple[AllocSet, AllocSet]:
        """Drop terminal allocs from previous job versions (batch only —
        service jobs account for them via name reuse)."""
        if not self.batch:
            return s, {}
        keep, ignore = {}, {}
        for k, a in s.items():
            older = a.job is not None and (
                a.job.version < self.job.version
                or a.job.create_index < self.job.create_index)
            if older and a.terminal_status():
                ignore[k] = a
            else:
                keep[k] = a
        return keep, ignore

    def _handle_group_canaries(self, all_allocs: AllocSet, du: DesiredUpdates
                               ) -> Tuple[AllocSet, AllocSet]:
        """Stop canaries from old/failed deployments; return the current
        deployment's live canaries."""
        stop_ids: List[str] = []
        if self.old_deployment is not None:
            for state in self.old_deployment.task_groups.values():
                if not state.promoted:
                    stop_ids.extend(state.placed_canaries)
        if (self.deployment is not None
                and self.deployment.status == DEPLOYMENT_STATUS_FAILED):
            for state in self.deployment.task_groups.values():
                if not state.promoted:
                    stop_ids.extend(state.placed_canaries)
        stop_set = rutil.from_keys(all_allocs, stop_ids)
        stop_set = rutil.filter_non_terminal(stop_set)
        self._mark_stop(stop_set, "", ALLOC_NOT_NEEDED)
        du.stop += len(stop_set)
        all_allocs = rutil.difference(all_allocs, stop_set)

        canaries: AllocSet = {}
        if self.deployment is not None:
            ids: List[str] = []
            for state in self.deployment.task_groups.values():
                ids.extend(state.placed_canaries)
            canaries = rutil.from_keys(all_allocs, ids)
            untainted, migrate, lost = rutil.filter_by_tainted(
                canaries, self.tainted_nodes)
            self._mark_stop(migrate, "", ALLOC_MIGRATING)
            self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)
            canaries = untainted
            all_allocs = rutil.difference(all_allocs, migrate, lost)
        return canaries, all_allocs

    def _compute_stop(self, tg: TaskGroup, name_index: rutil.AllocNameIndex,
                      untainted: AllocSet, migrate: AllocSet, lost: AllocSet,
                      canaries: AllocSet, canary_state: bool) -> AllocSet:
        stop: AllocSet = dict(lost)
        self._mark_stop(lost, ALLOC_CLIENT_LOST, ALLOC_LOST)

        if canary_state:
            untainted = rutil.difference(untainted, canaries)

        remove = len(untainted) + len(migrate) - tg.count
        if remove <= 0:
            return stop

        # don't stop running allocs when terminal ones already satisfy count
        untainted = rutil.filter_non_terminal(untainted)

        # after promotion, prefer stopping the old allocs that share a
        # canary's name
        if not canary_state and canaries:
            cnames = rutil.name_set(canaries)
            for a in rutil.name_order(rutil.difference(untainted, canaries)):
                if a.name in cnames:
                    stop[a.id] = a
                    self.result.stop.append(AllocStopResult(
                        alloc=a, status_description=ALLOC_NOT_NEEDED))
                    del untainted[a.id]
                    remove -= 1
                    if remove == 0:
                        return stop

        # prefer stopping migrating allocs over running ones
        if migrate:
            mnames = rutil.AllocNameIndex(self.job_id, tg.name, tg.count,
                                          migrate)
            remove_names = mnames.highest(remove)
            for a in rutil.name_order(migrate):
                if a.name not in remove_names:
                    continue
                stop[a.id] = a
                self.result.stop.append(AllocStopResult(
                    alloc=a, status_description=ALLOC_NOT_NEEDED))
                del migrate[a.id]
                remove -= 1
                if remove == 0:
                    return stop

        # stop the highest name indexes
        remove_names = name_index.highest(remove)
        for a in rutil.name_order(untainted):
            if a.name in remove_names:
                stop[a.id] = a
                self.result.stop.append(AllocStopResult(
                    alloc=a, status_description=ALLOC_NOT_NEEDED))
                name_index.unset_index(a.index())
                del untainted[a.id]
                remove -= 1
                if remove == 0:
                    return stop

        # fallback: names didn't parse / duplicates — stop arbitrarily
        for a in rutil.name_order(untainted):
            stop[a.id] = a
            self.result.stop.append(AllocStopResult(
                alloc=a, status_description=ALLOC_NOT_NEEDED))
            name_index.unset_index(a.index())
            del untainted[a.id]
            remove -= 1
            if remove == 0:
                return stop
        return stop

    def _compute_updates(self, tg: TaskGroup, untainted: AllocSet
                         ) -> Tuple[AllocSet, AllocSet, AllocSet]:
        """Classify untainted allocs as (ignore, inplace, destructive)."""
        ignore: AllocSet = {}
        inplace: AllocSet = {}
        destructive: AllocSet = {}
        # classification is entirely the update fn's call (reference:
        # computeUpdates defers to allocUpdateFn; the same-version
        # short-circuit lives in util.go:846 genericAllocUpdateFn)
        for k, a in untainted.items():
            ig, destroy, updated = self.alloc_update_fn(a, self.job, tg)
            if ig:
                ignore[k] = a
            elif destroy:
                destructive[k] = a
            else:
                inplace[k] = a
                if updated is not None:
                    self.result.inplace_update.append(updated)
        return ignore, inplace, destructive

    def _compute_limit(self, tg: TaskGroup, untainted: AllocSet,
                       destructive: AllocSet, migrate: AllocSet,
                       canary_state: bool) -> int:
        if tg.update is None or len(destructive) + len(migrate) == 0:
            return tg.count
        if self.deployment_paused or self.deployment_failed:
            return 0
        if canary_state:
            return 0
        limit = tg.update.max_parallel
        if self.deployment is not None:
            part_of, _ = rutil.filter_by_deployment(untainted,
                                                    self.deployment.id)
            for a in part_of.values():
                if a.deployment_status is not None:
                    if a.deployment_status.is_unhealthy():
                        return 0
                    if not a.deployment_status.is_healthy():
                        limit -= 1
                else:
                    limit -= 1
        return max(0, limit)

    def _compute_placements(self, tg: TaskGroup,
                            name_index: rutil.AllocNameIndex,
                            untainted: AllocSet, migrate: AllocSet,
                            reschedule: AllocSet) -> List[AllocPlaceResult]:
        place: List[AllocPlaceResult] = []
        for a in rutil.name_order(reschedule):
            canary = (a.deployment_status is not None
                      and a.deployment_status.canary)
            place.append(AllocPlaceResult(
                name=a.name, task_group=tg, previous_alloc=a,
                reschedule=True, canary=canary))
        existing = len(untainted) + len(migrate) + len(reschedule)
        if existing < tg.count:
            for name in name_index.next(tg.count - existing):
                place.append(AllocPlaceResult(name=name, task_group=tg))
        return place

    def _handle_delayed_reschedules(
            self, resched_later: List[Tuple[Allocation, float]],
            all_allocs: AllocSet, group: str) -> None:
        """Batch delayed reschedules into follow-up evals: allocs whose
        eligible times fall within a 5 s window share one wait-until eval;
        each alloc is annotated with its follow-up eval id."""
        if not resched_later:
            return
        resched_later.sort(key=lambda t: t[1])
        evals: List[Evaluation] = []
        batches: List[List[Allocation]] = []
        batch_start = -math.inf
        for a, when in resched_later:
            if when - batch_start > BATCHED_FAILED_ALLOC_WINDOW_S:
                batch_start = when
                ev = Evaluation(
                    namespace=self.job.namespace, priority=self.job.priority,
                    type=self.job.type,
                    triggered_by=EVAL_TRIGGER_FAILED_FOLLOW_UP,
                    job_id=self.job.id, status=EVAL_STATUS_PENDING,
                    wait_until=when)
                evals.append(ev)
                batches.append([])
            batches[-1].append(a)
        self.result.desired_followup_evals.setdefault(group, []).extend(evals)
        for ev, members in zip(evals, batches):
            for a in members:
                updated = _shallow_copy_alloc(a)
                updated.follow_up_eval_id = ev.id
                self.result.attribute_updates[updated.id] = updated


def _shallow_copy_alloc(a: Allocation) -> Allocation:
    import copy
    return copy.copy(a)
