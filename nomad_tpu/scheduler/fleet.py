"""Fleet solve: fuse a batch of evals into ONE device solve.

This is the TPU recast of the reference's optimistic worker concurrency
(SURVEY §2.5): where the reference runs N goroutines each solving one
eval against its own snapshot — conflicts surfacing only at the plan
applier — this path drains up to K ready evals (one per job, by broker
construction), reconciles each on the host, and solves ALL their
placements in a single kernel invocation. Placements from different evals
see each other inside the solve (the scan's shared `used` carry), so
intra-batch plan conflicts largely vanish instead of being retried.

Shared world note: the packed usage comes from the common snapshot;
capacity freed by an eval's own stops becomes visible only after its plan
commits. An eval that fails a placement or partially commits falls back
to the single-eval path, which sees its stops.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional, Tuple

from ..structs import (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED, Allocation,
                       Evaluation, JOB_TYPE_BATCH, JOB_TYPE_SERVICE)
from .generic import GenericScheduler, _VALID_TRIGGERS

#: hard ceiling on evals fused into one coordinator round — beyond
#: this the ask tensor gets big enough that solve wall grows past the
#: SLO budget the BatchController sized the member batches for
DEFAULT_MAX_FUSED = 128

#: per-round wall breakdown stages (ISSUE 19).  `dequeue` is recorded
#: by the worker loop (the broker wait isn't visible here); the fleet
#: phases record the rest.  `device` is the union of in-order device
#: intervals — under pipelining it overlaps reconcile/pack of the next
#: round, so the stages deliberately do NOT sum to round wall.
ROUND_STAGES = ("dequeue", "reconcile", "pack", "dispatch", "device",
                "fetch", "plan_build", "apply")


def record_stage_metrics(stages: Dict[str, float],
                         prefix: str = "coordinator.stage") -> None:
    """Publish one round's stage breakdown as metrics histograms
    (explicit latency buckets, surfaced at /v1/metrics and consumed by
    bench.py --scaleout)."""
    from ..utils.metrics import global_metrics as _m
    for name, v in stages.items():
        _m.observe_hist(f"{prefix}.{name}_s", float(v))


def form_lanes(members: List, width: int, key_fn) -> List:
    """Conflict-aware chunk formation (ISSUE 20): order `members` so
    that every consecutive `width`-block — one lane chunk of the
    chunked scan-of-vmap — holds members with pairwise-disjoint
    conflict footprints wherever the workload allows.

    `key_fn(member)` returns the member's footprint: an iterable of
    hashable atoms (candidate-shortlist node ids, (dc, zone) pins,
    namespace keys — whatever the caller can compute cheaply).  Two
    members conflict when their footprints intersect; conflicting
    members sharing a chunk solve against the same stale usage
    snapshot and bounce at the cross-lane revalidation, so the former
    keeps them in DIFFERENT chunks — serialized through the scan
    carry — and fills each chunk from one independent set.

    Greedy first-fit coloring: each color class keeps the union of
    its members' footprints, and a member joins the first class whose
    union it does not touch (disjoint-from-union implies pairwise
    disjoint).  Classes then emit whole chunks; ragged tails are
    re-packed across classes with the same disjointness check, so
    conflicting tails serialize instead of sharing a chunk.  Pure
    reorder: the result is a permutation of `members`, never a
    drop — a bounced lane is a retry, a dropped member is a lost
    eval."""
    if width <= 1 or len(members) <= width:
        return list(members)
    classes: List[List] = []          # [union_footprint, [members]]
    keys: Dict[int, frozenset] = {}
    for m in members:
        ks = frozenset(key_fn(m))
        keys[id(m)] = ks
        for cl in classes:
            if not (cl[0] & ks):
                cl[0] |= ks
                cl[1].append(m)
                break
        else:
            classes.append([set(ks), [m]])
    out: List = []
    tails: List = []
    for _uni, group in classes:
        n_full = (len(group) // width) * width
        out.extend(group[:n_full])
        tails.extend(group[n_full:])
    while tails:
        chunk: List = []
        uni: set = set()
        rest: List = []
        for m in tails:
            ks = keys[id(m)]
            if len(chunk) < width and not (uni & ks):
                chunk.append(m)
                uni |= ks
            else:
                rest.append(m)
        out.extend(chunk)
        tails = rest
    return out


class LaneWidthController:
    """Adaptive lane width for the chunked scan-of-vmap (ISSUE 20):
    pow2 widths in [1, max_width], one step per observation.

    Fed by the two signals the issue names: the measured cross-lane
    bounce rate (ResidentSolver.lane_counters) and the PR-19 stage
    accounting (is `device` still the dominant stage?).  Widen when
    lanes are winning — bounce below `widen_below` AND the device
    stage dominant, so more in-kernel parallelism attacks the actual
    bottleneck; narrow when revalidation bounces above `narrow_above`
    — a bounced lane re-solves through the retry path, so a high
    bounce rate makes wide chunks slower than the serial depth they
    save.  `patience` consecutive agreeing rounds are required per
    step (hysteresis: one conflicted round must not collapse L), and
    any disagreeing round resets the streak."""

    def __init__(self, max_width: int = 8, start: int = 2,
                 widen_below: float = 0.05, narrow_above: float = 0.25,
                 patience: int = 2):
        self.max_width = max(1, int(max_width))
        self.width = min(max(1, int(start)), self.max_width)
        self.widen_below = float(widen_below)
        self.narrow_above = float(narrow_above)
        self.patience = max(1, int(patience))
        self._streak = 0          # +n widen votes, -n narrow votes
        #: observation log (bounce_rate, device_frac, width) — the
        #: bench's lane leg reports the trajectory
        self.history: List[Tuple[float, float, int]] = []

    def record(self, bounce_rate: float,
               device_frac: float = 1.0) -> int:
        """Feed one round's signals; returns the (possibly stepped)
        width to use for the next round."""
        self.history.append((float(bounce_rate), float(device_frac),
                             self.width))
        if bounce_rate > self.narrow_above:
            self._streak = min(self._streak, 0) - 1
        elif bounce_rate < self.widen_below and device_frac >= 0.5:
            self._streak = max(self._streak, 0) + 1
        else:
            self._streak = 0
        if self._streak >= self.patience and self.width < self.max_width:
            self.width <<= 1
            self._streak = 0
        elif self._streak <= -self.patience and self.width > 1:
            self.width >>= 1
            self._streak = 0
        return self.width


class _Entry:
    def __init__(self, ev: Evaluation, token: str,
                 sched: GenericScheduler):
        self.ev = ev
        self.token = token
        self.sched = sched
        self.prep = None
        self.ask_base = 0
        self.err: Optional[str] = None


class _SolveView:
    """Per-eval slice of the fused SolveOutput with rebased ask indexes."""

    def __init__(self, placements, class_eligibility):
        self.placements = placements
        self.class_eligibility = class_eligibility
        self.trace: dict = {}       # shared fused-solve counters


class _FleetRound:
    """One fused round in flight between the pipeline phases: built by
    `fleet_begin` (reconcile), armed by `fleet_dispatch` (kernel
    launch, no fetch), completed by `fleet_finish` (fetch + fan-back +
    finalize).  `stages` collects the per-round wall breakdown
    (ROUND_STAGES keys, seconds)."""

    __slots__ = ("fused", "solvable", "snapshot", "nodes", "by_dc",
                 "allocs_by_node", "all_asks", "spans", "pending",
                 "stages", "t_dispatched", "t_fetch_done")

    def __init__(self) -> None:
        self.fused: List[_Entry] = []
        self.solvable: List[_Entry] = []
        self.snapshot = None
        self.nodes: List = []
        self.by_dc: Dict[str, int] = {}
        self.allocs_by_node = {}
        self.all_asks: List = []
        self.spans: Dict[str, object] = {}
        self.pending = None          # PendingSolve once dispatched
        self.stages: Dict[str, float] = {}
        self.t_dispatched = 0.0
        self.t_fetch_done = 0.0


def fleet_begin(server, worker, batch: List[Tuple[Evaluation, str]]
                ) -> Optional[_FleetRound]:
    """Reconcile phase: pause redeliveries, peel off evals the fused
    path can't carry (single-eval processed inline), build the shared
    world ONCE, and run every member's reconcile + ask assembly against
    it.  Returns None when nothing is left to fuse."""
    t0 = _time.perf_counter()
    # the fused pass can outlive the nack timeout for tail-of-batch
    # evals; hold the timers while we own the batch (explicit ack/nack
    # follows) — one lock hold per touched shard, not per eval
    server.broker.pause_nack_batch([(ev.id, tok) for ev, tok in batch])

    fused: List[_Entry] = []
    for ev, token in batch:
        if ev.type not in (JOB_TYPE_SERVICE, JOB_TYPE_BATCH) \
                or ev.triggered_by not in _VALID_TRIGGERS:
            worker._process(ev, token)
            continue
        fused.append(_Entry(ev, token, GenericScheduler(
            server.store, worker, batch=(ev.type == JOB_TYPE_BATCH),
            solver=worker.fleet_solver())))
    if not fused:
        return None

    rnd = _FleetRound()
    rnd.fused = fused
    wait_index = max(max(e.ev.modify_index, e.ev.snapshot_index)
                     for e in fused)
    server.store.wait_for_index(wait_index, timeout=5.0)
    snapshot = server.store.snapshot()
    rnd.snapshot = snapshot

    # one shared world for the whole batch — including the node-id map
    # and dc counts every member's prepare pass reads (the per-eval
    # rebuild of node_by_id over a 2k-node list was pure burn)
    nodes = [n for n in snapshot.nodes() if n.ready()]
    rnd.nodes = nodes
    node_by_id = {n.id: n for n in nodes}
    by_dc: Dict[str, int] = {}
    for n in nodes:
        by_dc[n.datacenter] = by_dc.get(n.datacenter, 0) + 1
    rnd.by_dc = by_dc
    allocs_by_node: Dict[str, List[Allocation]] = {}
    for n in nodes:
        live = [a for a in snapshot.allocs_by_node(n.id)
                if not a.terminal_status()]
        if live:
            allocs_by_node[n.id] = live
    rnd.allocs_by_node = allocs_by_node

    all_asks: List = []
    for e in fused:
        try:
            missing, err = e.sched._begin(e.ev, snapshot)
        except Exception as exc:
            e.err = f"scheduler error: {exc}"
            continue
        if err is not None:
            e.err = err
            continue
        if missing:
            # restrict to this job's datacenters via the ask's dc mask —
            # the shared node list spans all DCs
            prep = e.sched._prepare_placements(
                snapshot, missing, nodes=nodes, by_dc=by_dc,
                allocs_by_node=allocs_by_node, node_by_id=node_by_id)
            if prep is not None:
                _nodes, _by_dc, _abn, asks, ask_missing = prep
                e.prep = (missing, ask_missing)
                e.ask_base = len(all_asks)
                all_asks.extend(asks)
                rnd.solvable.append(e)
    rnd.all_asks = all_asks
    rnd.stages["reconcile"] = _time.perf_counter() - t0
    return rnd


def fleet_dispatch(server, worker, rnd: _FleetRound) -> None:
    """Dispatch phase: launch the fused kernel WITHOUT fetching.  After
    this returns the device is solving and the leader is free to
    reconcile the next round."""
    if not rnd.all_asks:
        return
    solvable = rnd.solvable
    snapshot = rnd.snapshot
    # fleet-mode proposed corrections: the shared world carries no
    # stop exclusions (capacity freed by an eval's own stops lands
    # after its plan commits — see module note); sticky probes from
    # every fused eval overlay the resident world's usage
    probes = [p for e in solvable for p in e.sched._sticky_probes]
    # in-kernel preemption only when EVERY fused eval's scheduler
    # type has it enabled (the pass can't gate per ask beyond the
    # priority delta); mixed configs keep the host-side fallback
    from .preemption import preemption_enabled
    cfg = snapshot.scheduler_config()
    preempt_ok = all(
        preemption_enabled(cfg, "batch" if e.sched.batch
                           else "service")
        for e in solvable)
    # one fused device solve, one solve span PER member trace: each
    # eval's timeline stays self-contained, the shared counters
    # (and fused_batch size) tie the members back together
    from ..utils.tracing import global_tracer as _tr
    for e in solvable:
        rnd.spans[e.ev.id] = _tr.stage(
            e.ev.id, "solve", job_id=e.ev.job_id, fused=True,
            fused_batch=len(solvable))
    rnd.pending = worker.fleet_solver().solve_async(
        rnd.nodes, rnd.all_asks, rnd.allocs_by_node, rnd.by_dc,
        snapshot=snapshot, proposed_delta=([], probes),
        preempt=preempt_ok)
    rnd.t_dispatched = rnd.pending.t_dispatched
    rnd.stages["pack"] = rnd.pending.pack_wall_s
    rnd.stages["dispatch"] = rnd.pending.dispatch_wall_s


def fleet_finish(server, worker, rnd: _FleetRound,
                 prev_fetch_done: float = 0.0) -> None:
    """Fetch + fan-back + finalize phase: block on the device result,
    slice it back to the member evals in ONE pass, finalize and
    ack/nack.  `prev_fetch_done` (pipelining): the previous round's
    fetch-completion stamp, so device time is accounted as the union of
    in-order device intervals rather than double-counted overlap."""
    out = None
    if rnd.pending is not None:
        out = rnd.pending.wait()
        rnd.t_fetch_done = _time.perf_counter()
        rnd.stages["fetch"] = rnd.pending.fetch_wall_s
        # device busy: this round's interval clipped to start after the
        # previous round's fetch completed (in-order execution)
        rnd.stages["device"] = max(
            0.0, rnd.t_fetch_done - max(rnd.t_dispatched,
                                        prev_fetch_done))
        serving = getattr(server, "serving", None)
        if serving is not None:
            # sizing-model feed: device time, NOT round wall — see
            # ServingTier.note_device_solve for why wall over-drains
            # the close rule under pipelining
            serving.note_device_solve(len(rnd.fused),
                                      rnd.stages["device"])

    snapshot = rnd.snapshot
    if out is not None and rnd.solvable:
        t0 = _time.perf_counter()
        # single-pass fan-back: each placement belongs to exactly one
        # member (ask ranges partition the fused ask list), so rebase
        # ask_index in place and bucket by owner — the old O(E*P) scan
        # with a copy per match dominated plan build at batch 128
        owner: List[int] = []
        for i, e in enumerate(rnd.solvable):
            owner.extend([i] * len(e.prep[1]))
        local: List[List] = [[] for _ in rnd.solvable]
        for p in out.placements:
            i = owner[p.ask_index]
            p.ask_index -= rnd.solvable[i].ask_base
            local[i].append(p)
        stage_attrs = {f"stage_{k}_s": round(v, 6)
                       for k, v in rnd.stages.items()}
        for i, e in enumerate(rnd.solvable):
            missing, ask_missing = e.prep
            base, n_local = e.ask_base, len(e.prep[1])
            view = _SolveView(
                local[i], out.class_eligibility[base:base + n_local])
            view.trace = dict(out.trace)
            view.trace.update(stage_attrs)
            e.sched._consume_solve(snapshot, view, rnd.nodes,
                                   rnd.allocs_by_node, missing,
                                   ask_missing,
                                   span=rnd.spans.get(e.ev.id))
        rnd.stages["plan_build"] = _time.perf_counter() - t0

    # finalize each eval; anything incomplete replays on the single path
    t0 = _time.perf_counter()
    acks: List[Tuple[str, str]] = []
    for e in rnd.fused:
        if e.err is not None:
            e.sched._set_status(EVAL_STATUS_FAILED, str(e.err))
            server.broker.nack(e.ev.id, e.token)
            continue
        try:
            done, err = e.sched._finalize({"made": False})
        except Exception as exc:
            done, err = False, f"finalize error: {exc}"
        if err is not None:
            e.sched._set_status(EVAL_STATUS_FAILED, str(err))
            server.broker.nack(e.ev.id, e.token)
        elif done:
            e.sched._set_status(EVAL_STATUS_COMPLETE, "")
            acks.append((e.ev.id, e.token))
        else:
            # partial commit / refresh: the single-eval retry loop owns it
            worker._process(e.ev, e.token)
    if acks:
        server.broker.ack_batch(acks)
    rnd.stages["apply"] = _time.perf_counter() - t0
    record_stage_metrics(rnd.stages)


def process_fleet(server, worker, batch: List[Tuple[Evaluation, str]]
                  ) -> None:
    """Process a dequeued eval batch with one fused solve. `worker` is the
    Planner handed to each scheduler and the fallback single-eval
    processor for anything the fused path can't finish.  Serialized
    composition of the three pipeline phases — the coordinator overlaps
    them across rounds instead."""
    rnd = fleet_begin(server, worker, batch)
    if rnd is None:
        return
    fleet_dispatch(server, worker, rnd)
    fleet_finish(server, worker, rnd)


class _FusedSubmission:
    """One worker's bulk batch parked on the coordinator: the worker
    blocks on `done` while the drain leader solves it (possibly fused
    with other workers' batches)."""

    __slots__ = ("worker", "batch", "done", "error")

    def __init__(self, worker, batch):
        self.worker = worker
        self.batch = batch
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


class SolveCoordinator:
    """Cross-worker solve fusion (ISSUE 17): N dequeue workers submit
    their bulk batches here instead of each running its own
    process_fleet — the first submitter becomes the drain leader,
    coalesces every queued submission into ONE combined batch, and runs
    the existing process_fleet path on a single pinned solver, so the
    device sees one big wave instead of N serialized small ones.
    Non-leaders park on a per-batch future.

    Lock discipline: `self._lock` guards only the queue/role flags and
    is NEVER held across the device solve or a submission wait — a
    submitter holding it through `done.wait()` would deadlock the drain
    leader trying to pick its batch up (the LOCK304 shape the lint
    fixture pins down).

    PIPELINING (ISSUE 19): the drain leader runs the solve as three
    phases (fleet_begin -> fleet_dispatch -> fleet_finish) and keeps
    ONE round in flight: while round b's fused kernel solves on the
    device, the leader reconciles and dispatches round b+1 — the same
    double-buffer `solve_stream_pipelined` runs inside a single solve,
    lifted to the serving path.  Round b+1's reconcile reads a snapshot
    that does not yet include round b's uncommitted plans; that is the
    SAME optimistic-concurrency model the reference's parallel workers
    (and PR 17's fused rounds) already use — conflicts surface at the
    plan applier and replay through the single-eval retry path.
    Submitters are released only when their round's finish phase
    completes, so at-least-once eval ownership is unchanged.

    `pause()`/`resume()` is the determinism hook for tests: paused, the
    coordinator only accumulates submissions; `resume()` drains them in
    one fused round, so a test can prove fusion produces placements
    identical to serialized singles."""

    def __init__(self, server, max_fused: int = DEFAULT_MAX_FUSED,
                 solve_fn=None, pipeline: bool = True,
                 dispatch_fn=None, finish_fn=None,
                 lane_former=None, lane_controller=None):
        self.server = server
        self.max_fused = max(1, int(max_fused))
        #: conflict-aware chunk formation (ISSUE 20): when set, the
        #: drain leader reorders each round's combined member list via
        #: `lane_former(members, width)` before dispatch, so the lane
        #: kernel's consecutive L-blocks hold non-conflicting members
        #: (`form_lanes` partially applied over a footprint key_fn is
        #: the standard former).  `lane_controller` supplies the width
        #: and is fed by the round's finish path (the bench's lane leg
        #: and the sharded drain both read the solver's lane counters
        #: there — the coordinator itself never blocks on a fetch to
        #: learn the bounce rate).
        self.lane_former = lane_former
        self.lane_controller = lane_controller
        #: (server, worker, combined_batch) -> None; serialized custom
        #: path (bench A/B legs, tests) — disables pipelining
        self.solve_fn = solve_fn
        #: split custom path: dispatch_fn(server, worker, batch) -> round
        #: handle (or None when nothing to solve), finish_fn(server,
        #: worker, round) -> None.  The bench injects a direct resident-
        #: solver pair here to measure pipelined fusion alone.
        self.dispatch_fn = dispatch_fn
        self.finish_fn = finish_fn
        self.pipeline = (bool(pipeline) and solve_fn is None) \
            or dispatch_fn is not None
        self._lock = threading.Lock()
        # signalled on every submission: the drain leader parks here
        # (briefly, bounded) when it has a round in flight but nothing
        # queued, so a submission landing during the device solve is
        # dispatched BEFORE the in-flight fetch instead of after it —
        # the difference between a back-to-back device and a bubble
        self._submitted = threading.Condition(self._lock)
        self._queue: List[_FusedSubmission] = []
        self._draining = False
        self._paused = False
        # the single resident solver the combined waves run on: pinned
        # to the first drain leader's worker so every fused round reuses
        # one tensorized world + compile cache
        self._solve_worker = None

    def submit(self, worker, batch: List[Tuple[Evaluation, str]]) -> None:
        """Solve `batch`, fused with whatever other workers have queued.
        Blocks until the batch's evals are acked/nacked/fallen back;
        re-raises the drain error so the caller's nack path owns its
        own evals."""
        sub = self.submit_nowait(worker, batch)
        if not sub.done.wait(60.0):
            raise TimeoutError("fused solve coordinator timed out")
        if sub.error is not None:
            raise sub.error

    def submit_nowait(self, worker,
                      batch: List[Tuple[Evaluation, str]]
                      ) -> "_FusedSubmission":
        """Queue `batch` for fused solving and return its fan-back
        future: `done` fires after the batch's round completes its
        finish phase, `error` carries a drain failure.  The FIRST
        submitter still becomes the drain leader and blocks inside
        `_drain`; every other caller returns immediately — the shape
        that keeps dequeue threads feeding the pipeline (a blocked
        submitter cannot fetch the next batch, so with blocking
        submits the device idles between rounds exactly as long as a
        dequeue takes).  Callers that fire-and-forget must arrange
        ack/nack inside the round itself (the bench's finish_fn does);
        callers that need results wait on the future — `submit` is
        that composition."""
        sub = _FusedSubmission(worker, batch)
        with self._lock:
            self._queue.append(sub)
            self._submitted.notify()
            leader = not self._draining and not self._paused
            if leader:
                self._draining = True
        if leader:
            self._drain(worker)
        return sub

    def pause(self) -> None:
        """Hold submissions without draining (test/chaos hook)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        """Release a pause; the resuming thread drains the backlog."""
        with self._lock:
            self._paused = False
            leader = not self._draining and bool(self._queue)
            if leader:
                self._draining = True
        if leader:
            self._drain(None)

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def _drain(self, worker) -> None:
        """Drain leader: fuse queued submissions round by round until
        the queue is empty (submissions landing mid-solve join the next
        round).  The role flag hand-off is atomic with the queue check,
        so a submission is never left behind without a drainer.

        Pipelined mode keeps one round in flight: each iteration
        dispatches round b+1 FIRST (the device starts solving), then
        finishes round b (fetch + fan-back + ack) — so the Python
        reconcile/plan work of every round overlaps the device solve of
        its neighbor.  The leader never returns with a round in flight,
        and a submitter's `done` fires only after its round's finish
        phase (no eval is released between dispatch and fetch)."""
        from ..utils.metrics import global_metrics as _m
        # (submitters, round handle) of the dispatched-not-fetched round
        inflight: Optional[Tuple[List[_FusedSubmission], object]] = None
        prev_fetch_done = 0.0
        while True:
            with self._lock:
                if inflight is not None and not self._queue \
                        and not self._paused:
                    # a round is solving on the device and the queue is
                    # dry: the fetch below would block until the device
                    # finishes anyway, so give a concurrent submitter a
                    # bounded beat to land — a submission caught here is
                    # dispatched UNDER the in-flight solve (back-to-back
                    # device) instead of after its fetch (a bubble the
                    # size of a dispatch).  Condition.wait releases the
                    # lock, so submitters are never blocked out.
                    self._submitted.wait(0.002)
                dry = self._paused or not self._queue
                if dry and inflight is None:
                    self._draining = False
                    return
                round_subs: List[_FusedSubmission] = []
                if not dry:
                    total = 0
                    while self._queue and total < self.max_fused:
                        s = self._queue.pop(0)
                        round_subs.append(s)
                        total += len(s.batch)
                    if self._solve_worker is None:
                        self._solve_worker = worker or round_subs[0].worker
                solve_worker = self._solve_worker
            rnd = None
            if round_subs:
                combined = [pair for s in round_subs for pair in s.batch]
                if self.lane_former is not None:
                    w = (self.lane_controller.width
                         if self.lane_controller is not None else 0)
                    combined = self.lane_former(combined, w)
                _m.add_sample("coordinator.fused_evals",
                              float(len(combined)))
                if len(round_subs) > 1:
                    _m.incr_counter("coordinator.cross_worker_rounds")
                _m.incr_counter("coordinator.rounds")
                if not self.pipeline:
                    # serialized path (legacy solve_fn or pipeline off):
                    # run the round end to end; nothing ever in flight
                    try:
                        (self.solve_fn or process_fleet)(
                            self.server, solve_worker, combined)
                    except Exception as exc:
                        # each submitter nacks its OWN evals from its
                        # worker loop's failure path — the coordinator
                        # only relays
                        for s in round_subs:
                            s.error = exc
                    finally:
                        for s in round_subs:
                            s.done.set()
                    continue
                try:
                    if self.dispatch_fn is not None:
                        rnd = self.dispatch_fn(self.server, solve_worker,
                                               combined)
                    else:
                        rnd = fleet_begin(self.server, solve_worker,
                                          combined)
                        if rnd is not None:
                            fleet_dispatch(self.server, solve_worker,
                                           rnd)
                except Exception as exc:
                    for s in round_subs:
                        s.error = exc
                        s.done.set()
                    round_subs, rnd = [], None
                if round_subs and rnd is None:
                    # nothing fused (every eval took the single path
                    # inside begin): the round is already complete
                    for s in round_subs:
                        s.done.set()
                    round_subs = []
            # round b's device solve has been running while round b+1
            # reconciled + dispatched above; finish it now and release
            # its submitters
            if inflight is not None:
                prev_fetch_done = self._finish_inflight(
                    solve_worker, inflight, prev_fetch_done)
            inflight = (round_subs, rnd) if round_subs else None

    def _finish_inflight(self, worker, inflight, prev_fetch_done: float
                         ) -> float:
        subs, rnd = inflight
        t_done = prev_fetch_done
        try:
            if self.finish_fn is not None:
                self.finish_fn(self.server, worker, rnd)
            else:
                fleet_finish(self.server, worker, rnd,
                             prev_fetch_done=prev_fetch_done)
            t_done = getattr(rnd, "t_fetch_done", 0.0) or prev_fetch_done
        except Exception as exc:
            for s in subs:
                s.error = exc
        finally:
            for s in subs:
                s.done.set()
        return t_done
