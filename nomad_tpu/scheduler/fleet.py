"""Fleet solve: fuse a batch of evals into ONE device solve.

This is the TPU recast of the reference's optimistic worker concurrency
(SURVEY §2.5): where the reference runs N goroutines each solving one
eval against its own snapshot — conflicts surfacing only at the plan
applier — this path drains up to K ready evals (one per job, by broker
construction), reconciles each on the host, and solves ALL their
placements in a single kernel invocation. Placements from different evals
see each other inside the solve (the scan's shared `used` carry), so
intra-batch plan conflicts largely vanish instead of being retried.

Shared world note: the packed usage comes from the common snapshot;
capacity freed by an eval's own stops becomes visible only after its plan
commits. An eval that fails a placement or partially commits falls back
to the single-eval path, which sees its stops.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..structs import (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED, Allocation,
                       Evaluation, JOB_TYPE_BATCH, JOB_TYPE_SERVICE)
from .generic import GenericScheduler, _VALID_TRIGGERS

#: hard ceiling on evals fused into one coordinator round — beyond
#: this the ask tensor gets big enough that solve wall grows past the
#: SLO budget the BatchController sized the member batches for
DEFAULT_MAX_FUSED = 128


class _Entry:
    def __init__(self, ev: Evaluation, token: str,
                 sched: GenericScheduler):
        self.ev = ev
        self.token = token
        self.sched = sched
        self.prep = None
        self.ask_base = 0
        self.err: Optional[str] = None


class _SolveView:
    """Per-eval slice of the fused SolveOutput with rebased ask indexes."""

    def __init__(self, placements, class_eligibility):
        self.placements = placements
        self.class_eligibility = class_eligibility
        self.trace: dict = {}       # shared fused-solve counters


def process_fleet(server, worker, batch: List[Tuple[Evaluation, str]]
                  ) -> None:
    """Process a dequeued eval batch with one fused solve. `worker` is the
    Planner handed to each scheduler and the fallback single-eval
    processor for anything the fused path can't finish."""
    # the fused pass can outlive the nack timeout for tail-of-batch evals;
    # hold the timers while we own the batch (explicit ack/nack follows)
    for ev, token in batch:
        server.broker.pause_nack_timeout(ev.id, token)

    fused: List[_Entry] = []
    for ev, token in batch:
        if ev.type not in (JOB_TYPE_SERVICE, JOB_TYPE_BATCH) \
                or ev.triggered_by not in _VALID_TRIGGERS:
            worker._process(ev, token)
            continue
        fused.append(_Entry(ev, token, GenericScheduler(
            server.store, worker, batch=(ev.type == JOB_TYPE_BATCH),
            solver=worker.fleet_solver())))
    if not fused:
        return

    wait_index = max(max(e.ev.modify_index, e.ev.snapshot_index)
                     for e in fused)
    server.store.wait_for_index(wait_index, timeout=5.0)
    snapshot = server.store.snapshot()

    # one shared world for the whole batch
    nodes = [n for n in snapshot.nodes() if n.ready()]
    by_dc: Dict[str, int] = {}
    for n in nodes:
        by_dc[n.datacenter] = by_dc.get(n.datacenter, 0) + 1
    allocs_by_node: Dict[str, List[Allocation]] = {}
    for n in nodes:
        live = [a for a in snapshot.allocs_by_node(n.id)
                if not a.terminal_status()]
        if live:
            allocs_by_node[n.id] = live

    all_asks = []
    all_ask_missing = []
    solvable: List[_Entry] = []
    for e in fused:
        try:
            missing, err = e.sched._begin(e.ev, snapshot)
        except Exception as exc:
            e.err = f"scheduler error: {exc}"
            continue
        if err is not None:
            e.err = err
            continue
        if missing:
            # restrict to this job's datacenters via the ask's dc mask —
            # the shared node list spans all DCs
            prep = e.sched._prepare_placements(
                snapshot, missing, nodes=nodes, by_dc=by_dc,
                allocs_by_node=allocs_by_node)
            if prep is not None:
                _nodes, _by_dc, _abn, asks, ask_missing = prep
                e.prep = (missing, ask_missing)
                e.ask_base = len(all_asks)
                all_asks.extend(asks)
                all_ask_missing.extend(ask_missing)
                solvable.append(e)

    out = None
    spans = {}
    if all_asks:
        # fleet-mode proposed corrections: the shared world carries no
        # stop exclusions (capacity freed by an eval's own stops lands
        # after its plan commits — see module note); sticky probes from
        # every fused eval overlay the resident world's usage
        probes = [p for e in solvable for p in e.sched._sticky_probes]
        # in-kernel preemption only when EVERY fused eval's scheduler
        # type has it enabled (the pass can't gate per ask beyond the
        # priority delta); mixed configs keep the host-side fallback
        from .preemption import preemption_enabled
        cfg = snapshot.scheduler_config()
        preempt_ok = all(
            preemption_enabled(cfg, "batch" if e.sched.batch
                               else "service")
            for e in solvable)
        # one fused device solve, one solve span PER member trace: each
        # eval's timeline stays self-contained, the shared counters
        # (and fused_batch size) tie the members back together
        from ..utils.tracing import global_tracer as _tr
        for e in solvable:
            spans[e.ev.id] = _tr.stage(
                e.ev.id, "solve", job_id=e.ev.job_id, fused=True,
                fused_batch=len(solvable))
        out = worker.fleet_solver().solve(nodes, all_asks, allocs_by_node,
                                          by_dc, snapshot=snapshot,
                                          proposed_delta=([], probes),
                                          preempt=preempt_ok)

    for e in solvable:
        missing, ask_missing = e.prep
        n_local = len(ask_missing)
        local_placements = []
        for p in out.placements:
            if e.ask_base <= p.ask_index < e.ask_base + n_local:
                import copy
                p2 = copy.copy(p)
                p2.ask_index = p.ask_index - e.ask_base
                local_placements.append(p2)
        view = _SolveView(
            local_placements,
            out.class_eligibility[e.ask_base:e.ask_base + n_local])
        view.trace = dict(out.trace)
        e.sched._consume_solve(snapshot, view, nodes, allocs_by_node,
                               missing, ask_missing,
                               span=spans.get(e.ev.id))

    # finalize each eval; anything incomplete replays on the single path
    for e in fused:
        if e.err is not None:
            e.sched._set_status(EVAL_STATUS_FAILED, str(e.err))
            server.broker.nack(e.ev.id, e.token)
            continue
        try:
            done, err = e.sched._finalize({"made": False})
        except Exception as exc:
            done, err = False, f"finalize error: {exc}"
        if err is not None:
            e.sched._set_status(EVAL_STATUS_FAILED, str(err))
            server.broker.nack(e.ev.id, e.token)
        elif done:
            e.sched._set_status(EVAL_STATUS_COMPLETE, "")
            server.broker.ack(e.ev.id, e.token)
        else:
            # partial commit / refresh: the single-eval retry loop owns it
            worker._process(e.ev, e.token)


class _FusedSubmission:
    """One worker's bulk batch parked on the coordinator: the worker
    blocks on `done` while the drain leader solves it (possibly fused
    with other workers' batches)."""

    __slots__ = ("worker", "batch", "done", "error")

    def __init__(self, worker, batch):
        self.worker = worker
        self.batch = batch
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


class SolveCoordinator:
    """Cross-worker solve fusion (ISSUE 17): N dequeue workers submit
    their bulk batches here instead of each running its own
    process_fleet — the first submitter becomes the drain leader,
    coalesces every queued submission into ONE combined batch, and runs
    the existing process_fleet path on a single pinned solver, so the
    device sees one big wave instead of N serialized small ones.
    Non-leaders park on a per-batch future.

    Lock discipline: `self._lock` guards only the queue/role flags and
    is NEVER held across the device solve or a submission wait — a
    submitter holding it through `done.wait()` would deadlock the drain
    leader trying to pick its batch up (the LOCK304 shape the lint
    fixture pins down).

    `pause()`/`resume()` is the determinism hook for tests: paused, the
    coordinator only accumulates submissions; `resume()` drains them in
    one fused round, so a test can prove fusion produces placements
    identical to serialized singles."""

    def __init__(self, server, max_fused: int = DEFAULT_MAX_FUSED,
                 solve_fn=None):
        self.server = server
        self.max_fused = max(1, int(max_fused))
        #: (server, worker, combined_batch) -> None; defaults to the
        #: scheduler-plane process_fleet — the bench injects a direct
        #: resident-solver path here to measure fusion alone
        self.solve_fn = solve_fn
        self._lock = threading.Lock()
        self._queue: List[_FusedSubmission] = []
        self._draining = False
        self._paused = False
        # the single resident solver the combined waves run on: pinned
        # to the first drain leader's worker so every fused round reuses
        # one tensorized world + compile cache
        self._solve_worker = None

    def submit(self, worker, batch: List[Tuple[Evaluation, str]]) -> None:
        """Solve `batch`, fused with whatever other workers have queued.
        Blocks until the batch's evals are acked/nacked/fallen back;
        re-raises the drain error so the caller's nack path owns its
        own evals."""
        sub = _FusedSubmission(worker, batch)
        with self._lock:
            self._queue.append(sub)
            leader = not self._draining and not self._paused
            if leader:
                self._draining = True
        if leader:
            self._drain(worker)
        if not sub.done.wait(60.0):
            raise TimeoutError("fused solve coordinator timed out")
        if sub.error is not None:
            raise sub.error

    def pause(self) -> None:
        """Hold submissions without draining (test/chaos hook)."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        """Release a pause; the resuming thread drains the backlog."""
        with self._lock:
            self._paused = False
            leader = not self._draining and bool(self._queue)
            if leader:
                self._draining = True
        if leader:
            self._drain(None)

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def _drain(self, worker) -> None:
        """Drain leader: fuse queued submissions round by round until
        the queue is empty (submissions landing mid-solve join the next
        round).  The role flag hand-off is atomic with the queue check,
        so a submission is never left behind without a drainer."""
        from ..utils.metrics import global_metrics as _m
        while True:
            with self._lock:
                if self._paused or not self._queue:
                    self._draining = False
                    return
                round_subs: List[_FusedSubmission] = []
                total = 0
                while self._queue and total < self.max_fused:
                    s = self._queue.pop(0)
                    round_subs.append(s)
                    total += len(s.batch)
                if self._solve_worker is None:
                    self._solve_worker = worker or round_subs[0].worker
                solve_worker = self._solve_worker
            combined = [pair for s in round_subs for pair in s.batch]
            _m.add_sample("coordinator.fused_evals", float(len(combined)))
            if len(round_subs) > 1:
                _m.incr_counter("coordinator.cross_worker_rounds")
            _m.incr_counter("coordinator.rounds")
            try:
                (self.solve_fn or process_fleet)(
                    self.server, solve_worker, combined)
            except Exception as exc:
                # each submitter nacks its OWN evals from its worker
                # loop's failure path — the coordinator only relays
                for s in round_subs:
                    s.error = exc
            finally:
                for s in round_subs:
                    s.done.set()
