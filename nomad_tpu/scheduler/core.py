"""Core "scheduler": administrative GC driven by core evals.

Reference: nomad/core_sched.go — Process :46, jobGC :84, evalGC :222,
nodeGC :425, deploymentGC :536, forceGC :67, allocGCEligible :648.
Core evals are enqueued by the leader's periodic timers (leader.go:513
schedulePeriodic) and by explicit force-GC; they carry the GC kind in
job_id. Time cutoffs map to indexes through the server's TimeTable.
"""
from __future__ import annotations

import time as _time
from typing import List, Optional, Tuple

from ..structs import (ALLOC_CLIENT_FAILED, ALLOC_CLIENT_RUNNING,
                       ALLOC_DESIRED_STOP, JOB_STATUS_DEAD, JOB_TYPE_BATCH,
                       Allocation, Evaluation, Job)

CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_DEPLOYMENT_GC = "deployment-gc"
CORE_JOB_FORCE_GC = "force-gc"

_MAX_INDEX = 2**62


def alloc_gc_eligible(a: Allocation, job: Optional[Job], gc_time: float,
                      threshold_index: int) -> bool:
    """reference: core_sched.go:648 allocGCEligible."""
    if not a.terminal_status() or a.modify_index > threshold_index:
        return False
    if a.client_status == ALLOC_CLIENT_RUNNING:
        return False
    if job is None or job.stop or job.status == JOB_STATUS_DEAD:
        return True
    if a.desired_status == ALLOC_DESIRED_STOP:
        return True
    if a.client_status != ALLOC_CLIENT_FAILED:
        return True
    tg = job.lookup_task_group(a.task_group)
    policy = tg.reschedule_policy if tg else None
    if policy is None or (not policy.unlimited and policy.attempts == 0):
        return True
    if a.next_allocation:
        # reschedule information has been carried forward
        return True
    if policy.unlimited:
        return False
    events = (a.reschedule_tracker.events
              if a.reschedule_tracker else [])
    if not events:
        return False
    # don't GC while the latest attempt is inside the policy interval
    return gc_time - events[-1].reschedule_time > policy.interval_s


class CoreScheduler:
    """Processes JOB_TYPE_CORE evals against a state snapshot, issuing
    reaps through the server's write paths (the leader-RPC analog)."""

    def __init__(self, server, snapshot):
        self.server = server
        self.snap = snapshot

    def process(self, ev: Evaluation) -> None:
        kind = ev.job_id.split(":")[0]
        if kind == CORE_JOB_EVAL_GC:
            self.eval_gc(ev)
        elif kind == CORE_JOB_NODE_GC:
            self.node_gc(ev)
        elif kind == CORE_JOB_JOB_GC:
            self.job_gc(ev)
        elif kind == CORE_JOB_DEPLOYMENT_GC:
            self.deployment_gc(ev)
        elif kind == CORE_JOB_FORCE_GC:
            self.force_gc(ev)
        else:
            raise ValueError(f"core scheduler cannot handle job {ev.job_id!r}")

    def force_gc(self, ev: Evaluation) -> None:
        self.job_gc(ev)
        self.eval_gc(ev)
        self.deployment_gc(ev)
        # node GC last so the alloc tables are already cleared
        self.node_gc(ev)

    # ------------------------------------------------------------ cutoffs
    def _threshold(self, ev: Evaluation, threshold_s: float) -> int:
        if ev.job_id.split(":")[0] == CORE_JOB_FORCE_GC:
            return _MAX_INDEX
        cutoff = _time.time() - threshold_s
        return self.server.time_table.nearest_index(cutoff)

    # ------------------------------------------------------------- passes
    def eval_gc(self, ev: Evaluation) -> None:
        threshold = self._threshold(ev, self.server.eval_gc_threshold_s)
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for e in list(self.snap.evals()):
            gc, allocs = self._gc_eval(e, threshold, allow_batch=False)
            if gc:
                gc_evals.append(e.id)
            gc_allocs.extend(allocs)
        if gc_evals or gc_allocs:
            self.server.reap_evals(gc_evals, gc_allocs)

    def _gc_eval(self, e: Evaluation, threshold: int,
                 allow_batch: bool) -> Tuple[bool, List[str]]:
        """reference: core_sched.go:280 gcEval."""
        if not e.terminal_status() or e.modify_index > threshold:
            return False, []
        job = self.snap.job_by_id(e.namespace, e.job_id)
        allocs = self.snap.allocs_by_eval(e.id)
        if e.type == JOB_TYPE_BATCH:
            # a running batch job's terminal allocs must survive GC or the
            # scheduler would re-run them (core_sched.go:305)
            collect = (job is None
                       or (job.status == JOB_STATUS_DEAD
                           and (job.stop or allow_batch)))
            if not collect:
                old = [a.id for a in allocs
                       if a.job is not None and job is not None
                       and a.job.create_index < job.create_index
                       and a.terminal_status()]
                return False, old
        now = _time.time()
        gc_ids = []
        gc_ok = True
        for a in allocs:
            if alloc_gc_eligible(a, job, now, threshold):
                gc_ids.append(a.id)
            else:
                gc_ok = False
        return gc_ok, gc_ids

    def job_gc(self, ev: Evaluation) -> None:
        threshold = self._threshold(ev, self.server.job_gc_threshold_s)
        gc_jobs: List[Job] = []
        gc_evals: List[str] = []
        gc_allocs: List[str] = []
        for job in list(self.snap.jobs()):
            if not self._job_gc_eligible(job) or job.create_index > threshold:
                continue
            evals = self.snap.evals_by_job(job.namespace, job.id)
            all_gc = True
            job_evals: List[str] = []
            job_allocs: List[str] = []
            for e in evals:
                gc, allocs = self._gc_eval(e, threshold, allow_batch=True)
                if gc:
                    job_evals.append(e.id)
                    job_allocs.extend(allocs)
                else:
                    all_gc = False
                    break
            if all_gc:
                gc_jobs.append(job)
                gc_evals.extend(job_evals)
                gc_allocs.extend(job_allocs)
        if gc_evals or gc_allocs:
            self.server.reap_evals(gc_evals, gc_allocs)
        if gc_jobs:
            self.server.reap_jobs([(j.namespace, j.id) for j in gc_jobs])

    @staticmethod
    def _job_gc_eligible(job: Job) -> bool:
        """reference: state/schema.go:244 jobIsGCable — periodic and
        parameterized templates are GC'd on stop alone; other jobs must be
        dead AND either explicitly stopped or batch-typed (a dead-but-not-
        stopped service job keeps its definition)."""
        periodic_enabled = job.periodic is not None and job.periodic.enabled
        if job.is_parameterized() or periodic_enabled:
            return job.stop
        return (job.status == JOB_STATUS_DEAD
                and (job.stop or job.type == JOB_TYPE_BATCH))

    def node_gc(self, ev: Evaluation) -> None:
        threshold = self._threshold(ev, self.server.node_gc_threshold_s)
        gc_nodes: List[str] = []
        for node in list(self.snap.nodes()):
            if not node.terminal_status() or node.modify_index > threshold:
                continue
            allocs = self.snap.allocs_by_node(node.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            gc_nodes.append(node.id)
        if gc_nodes:
            self.server.reap_nodes(gc_nodes)

    def deployment_gc(self, ev: Evaluation) -> None:
        threshold = self._threshold(ev, self.server.deployment_gc_threshold_s)
        gc_deps: List[str] = []
        for dep in list(self.snap.deployments()):
            if dep.active() or dep.modify_index > threshold:
                continue
            allocs = self.snap.allocs_by_deployment(dep.id)
            if any(not a.terminal_status() for a in allocs):
                continue
            gc_deps.append(dep.id)
        if gc_deps:
            self.server.reap_deployments(gc_deps)
