"""Raft node: leader election, log replication, commit + apply.

Reference contract: hashicorp/raft as wired in nomad/server.go:1157
(setupRaft) and driven by nomad/leader.go (leadership loop). This is a
compact but real implementation: randomized election timeouts, terms and
votes persisted alongside the log, AppendEntries with the prev-entry
consistency check and conflict truncation, majority commit (only for
entries of the current term), snapshot install for lagging followers,
and log compaction.

Transports are pluggable: InProcTransport for tests (the reference
tests raft fully in-process too — nomad/testing.go:42) and the TCP
transport in nomad_tpu/rpc for real deployments.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .fsm import NOOP, StateFSM
from .log import LogEntry, RaftLog

# membership-change entry, applied by the raft layer itself (not the
# state FSM): payload = the full new peer list (one-at-a-time changes,
# raft §6 single-server membership change)
CONFIG = "::config"

ROLE_FOLLOWER = "follower"
ROLE_CANDIDATE = "candidate"
ROLE_LEADER = "leader"


class NotLeaderError(Exception):
    def __init__(self, leader_id: Optional[str]):
        super().__init__(f"not the leader (leader={leader_id})")
        self.leader_id = leader_id


@dataclass
class RaftConfig:
    node_id: str = "node-1"
    peers: List[str] = field(default_factory=list)   # includes self
    data_dir: Optional[str] = None
    election_timeout_s: Tuple[float, float] = (0.15, 0.30)
    heartbeat_interval_s: float = 0.05
    snapshot_threshold: int = 8192      # log entries before compaction
    # Durable by default: committed entries must survive power loss
    # (reference: raft-boltdb fsyncs every append).  Tests and
    # benchmarks that churn thousands of throwaway entries may opt out.
    fsync: bool = True
    # an empty-log member waits this long for an existing leader to
    # contact it before campaigning: a freshly ADDED server would
    # otherwise inflate its term pre-join and depose a healthy leader
    # on first contact (fresh full-cluster bootstraps just wait it out)
    join_grace_s: float = 1.0


class InProcTransport:
    """Direct-call transport: a registry of live nodes. Closed nodes are
    unreachable (simulates a crashed server)."""

    def __init__(self):
        self._nodes: Dict[str, "RaftNode"] = {}
        self._lock = threading.Lock()

    def register(self, node: "RaftNode") -> None:
        with self._lock:
            self._nodes[node.id] = node

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def call(self, target: str, method: str, *args):
        with self._lock:
            node = self._nodes.get(target)
        if node is None or not node.running:
            raise ConnectionError(f"peer {target} unreachable")
        return getattr(node, method)(*args)


class RaftNode:
    def __init__(self, config: RaftConfig, fsm: StateFSM,
                 transport: InProcTransport,
                 on_leader: Optional[Callable[[], None]] = None,
                 on_follower: Optional[Callable[[], None]] = None):
        self.cfg = config
        self.id = config.node_id
        self.fsm = fsm
        self.transport = transport
        self.on_leader = on_leader          # called OUTSIDE the lock
        self.on_follower = on_follower
        self.log = RaftLog(config.data_dir, fsync=config.fsync)

        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self.term = 0
        self.voted_for: Optional[str] = None
        self.role = ROLE_FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        self.snapshot_index = 0
        self.snapshot_term = 0
        self._events_lock = threading.Lock()
        self._next: Dict[str, int] = {}
        self._match: Dict[str, int] = {}
        # learners: replicated to, never counted toward quorum — the
        # catch-up phase before a membership add (raft §6 non-voters)
        self._staging: List[str] = []
        self.running = False
        self._closed = False
        self._threads: List[threading.Thread] = []
        self._deadline = 0.0
        self._meta_saved_commit = 0
        self._last_leader_contact = 0.0
        self._role_events: List[str] = []    # deferred callbacks

        self._meta_path = (os.path.join(config.data_dir, "raft.meta")
                           if config.data_dir else None)
        self._snap_path = (os.path.join(config.data_dir, "raft.snap")
                           if config.data_dir else None)
        self._restore_from_disk()
        transport.register(self)

    # ------------------------------------------------------- persistence
    def _save_meta_locked(self) -> None:
        self._meta_saved_commit = self.commit_index
        if not self._meta_path:
            return
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for,
                       "commit_index": self.commit_index,
                       "snapshot_index": self.snapshot_index,
                       "snapshot_term": self.snapshot_term,
                       "peers": list(self.cfg.peers)}, f)
        os.replace(tmp, self._meta_path)

    def _restore_from_disk(self) -> None:
        if self._meta_path and os.path.exists(self._meta_path):
            with open(self._meta_path, encoding="utf-8") as f:
                meta = json.load(f)
            self.term = meta.get("term", 0)
            self.voted_for = meta.get("voted_for")
            self.commit_index = meta.get("commit_index", 0)
            self.snapshot_index = meta.get("snapshot_index", 0)
            self.snapshot_term = meta.get("snapshot_term", 0)
            # membership survives log compaction through the metadata
            # (a config entry behind the snapshot point is gone)
            if meta.get("peers"):
                self.cfg.peers = list(meta["peers"])
        if self._snap_path and os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                self.fsm.restore(f.read())
            self.last_applied = self.snapshot_index
        # Single-voter clusters replay the whole log: every appended
        # entry was self-accepted, so none can conflict, and this
        # recovers commits made after the last meta write. Multi-node
        # members replay only the committed prefix (the uncommitted
        # tail is resolved by the leader's consistency check).
        single = len(self.cfg.peers) <= 1
        replay_to = self.log.last_index() if single else self.commit_index
        for e in self.log.slice_from(self.last_applied + 1,
                                     limit=1 << 30):
            if e.index > replay_to:
                break
            if e.etype == CONFIG:
                self.cfg.peers = list(e.payload)
            else:
                self.fsm.apply(e.index, e.etype, e.payload)
            self.last_applied = e.index
        if single:
            self.commit_index = max(self.commit_index, self.last_applied)

    # ------------------------------------------------------------ control
    def start(self) -> None:
        with self._lock:
            if self.running:
                return
            self.running = True
            self._reset_election_deadline_locked()
            if self.log.last_index() == 0 and self.term == 0:
                self._deadline += self.cfg.join_grace_s
            # thread handle guarded by _lock (the loop's first action
            # is to take it, so starting here just briefly blocks it)
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"raft-{self.id}")
            t.start()
            self._threads = [t]

    def stop(self) -> None:
        with self._lock:
            self.running = False
            self._closed = True
            self._save_meta_locked()
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
        self.transport.unregister(self.id)
        self.log.close()

    def is_leader(self) -> bool:
        with self._lock:
            return self.role == ROLE_LEADER

    def bootstrap_single(self, defer_events: bool = False) -> None:
        """Degenerate cluster of one: become leader immediately (used by
        the default single-server deployment). With defer_events the
        on_leader callback stays queued until fire_pending_role_events()
        — the Server constructor uses this so writes work immediately
        while leader services wait for start()."""
        with self._lock:
            if self.role == ROLE_LEADER:
                return
            self.term += 1
            self.voted_for = self.id
            self._become_leader_locked()
            self._save_meta_locked()
        if not defer_events:
            self._fire_role_events()

    def fire_pending_role_events(self) -> None:
        self._fire_role_events()

    # -------------------------------------------------------------- loop
    def _run(self) -> None:
        hb = self.cfg.heartbeat_interval_s
        while True:
            with self._lock:
                if not self.running:
                    return
                role = self.role
                now = time.monotonic()
                timed_out = now >= self._deadline
            if role == ROLE_LEADER:
                self._replicate_all()
                time.sleep(hb)
            elif timed_out:
                self._start_election()
            else:
                time.sleep(0.01)
            self._fire_role_events()

    def _reset_election_deadline_locked(self) -> None:
        lo, hi = self.cfg.election_timeout_s
        self._deadline = time.monotonic() + random.uniform(lo, hi)

    # ---------------------------------------------------------- election
    def _start_election(self) -> None:
        with self._lock:
            if not self.running:
                return
            self.role = ROLE_CANDIDATE
            self.term += 1
            self.voted_for = self.id
            self.leader_id = None
            term = self.term
            last_i = self.log.last_index()
            last_t = (self.log.term_at(last_i)
                      if last_i > self.snapshot_index
                      else self._snap_term())
            self._save_meta_locked()
            self._reset_election_deadline_locked()
        votes = 1
        for peer in self.cfg.peers:
            if peer == self.id:
                continue
            try:
                pterm, granted = self.transport.call(
                    peer, "rpc_request_vote", term, self.id, last_i, last_t)
            except ConnectionError:
                continue
            with self._lock:
                if pterm > self.term:
                    self._step_down_locked(pterm)
                    return
            if granted:
                votes += 1
        with self._lock:
            if (self.role == ROLE_CANDIDATE and self.term == term
                    and votes * 2 > len(self.cfg.peers or [self.id])):
                self._become_leader_locked()

    def _become_leader_locked(self) -> None:
        self.role = ROLE_LEADER
        self.leader_id = self.id
        last = self.log.last_index()
        for p in self.cfg.peers:
            self._next[p] = last + 1
            self._match[p] = 0
        self._match[self.id] = last
        # commit a noop barrier so the new term can commit prior-term
        # entries (raft's no-op-on-election rule)
        self._append_locked(NOOP, None)
        self._role_events.append("leader")

    def step_down(self) -> bool:
        """Voluntary leader step-down (the chaos plane's
        leader-failure hook, analog of raft leadership transfer):
        bump the term and drop to follower so the election timer
        picks a fresh leader.  No-op on non-leaders."""
        with self._lock:
            if self.role != ROLE_LEADER:
                return False
            self._step_down_locked(self.term + 1)
        self._fire_role_events()
        return True

    def _step_down_locked(self, term: int) -> None:
        was_leader = self.role == ROLE_LEADER
        self.term = term
        self.role = ROLE_FOLLOWER
        self.voted_for = None
        self._save_meta_locked()
        self._reset_election_deadline_locked()
        if was_leader:
            self._role_events.append("follower")

    def _fire_role_events(self) -> None:
        # _events_lock serializes callback execution across the _run loop
        # and peer RPC threads, so leader/follower transitions fire in
        # queue order — otherwise a flap could leave leader services
        # disabled on the actual leader
        with self._events_lock:
            while True:
                with self._lock:
                    if not self._role_events:
                        return
                    ev = self._role_events.pop(0)
                if ev == "leader" and self.on_leader:
                    self.on_leader()
                elif ev == "follower" and self.on_follower:
                    self.on_follower()

    def _snap_term(self) -> int:
        with self._lock:    # re-entrant; callers already hold it
            return self.snapshot_term

    # -------------------------------------------------------- replication
    def _append_locked(self, etype: str, payload: Any) -> int:
        index = self.log.last_index() + 1
        self.log.append([LogEntry(index, self.term, etype, payload)])
        self._match[self.id] = index
        return index

    def propose(self, etype: str, payload: Any,
                timeout: float = 10.0) -> int:
        """Append + replicate + wait for local apply. Raises
        NotLeaderError from followers (callers forward to the leader)."""
        with self._lock:
            if self._closed:
                raise NotLeaderError(None)
            if self.role != ROLE_LEADER:
                raise NotLeaderError(self.leader_id)
            index = self._append_locked(etype, payload)
            term = self.term
        return self._wait_applied(index, term, timeout)

    def propose_async(self, etype: str, payload: Any):
        """Append + kick replication WITHOUT waiting; returns
        (index, wait_fn) where wait_fn(timeout) blocks until the entry
        is applied locally.  The pipelined plan applier overlaps the
        consensus round trip of plan N with evaluating plan N+1
        (reference: plan_apply.go:71-178 applyPlan's async raft future
        + asyncPlanWait)."""
        with self._lock:
            if self._closed:
                raise NotLeaderError(None)
            if self.role != ROLE_LEADER:
                raise NotLeaderError(self.leader_id)
            index = self._append_locked(etype, payload)
            term = self.term
        single = len([p for p in self.cfg.peers or [self.id]]) <= 1
        if single:
            with self._lock:
                self._advance_commit_locked()
                self._apply_committed_locked()
            return index, (lambda timeout=10.0: index)
        kick = threading.Thread(target=self._replicate_all, daemon=True)
        kick.start()
        return index, (lambda timeout=10.0:
                       self._await_applied(index, term, timeout))

    def _wait_applied(self, index: int, term: int,
                      timeout: float) -> int:
        single = len([p for p in self.cfg.peers or [self.id]]) <= 1
        if single:
            with self._lock:
                self._advance_commit_locked()
                self._apply_committed_locked()
                return index
        self._replicate_all()
        return self._await_applied(index, term, timeout)

    def _await_applied(self, index: int, term: int,
                       timeout: float) -> int:
        deadline = time.monotonic() + timeout
        with self._lock:
            while self.last_applied < index:
                if self.role != ROLE_LEADER or self.term != term:
                    raise NotLeaderError(self.leader_id)
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise TimeoutError("proposal not committed in time")
                self._cv.wait(remain)
            return index

    def _replicate_all(self) -> None:
        with self._lock:
            targets = [p for p in list(self.cfg.peers)
                       + list(self._staging) if p != self.id]
        for peer in targets:
            self._replicate_one(peer)
        with self._lock:
            if self.role == ROLE_LEADER:
                self._advance_commit_locked()
                self._apply_committed_locked()

    def _replicate_one(self, peer: str) -> None:
        with self._lock:
            if self.role != ROLE_LEADER:
                return
            nxt = self._next.get(peer, self.log.last_index() + 1)
            if nxt <= self.snapshot_index:
                snap = self._read_snapshot()
                term = self.term
                snap_index = self.snapshot_index
                snap_term = self.snapshot_term
            else:
                snap = None
                prev = nxt - 1
                prev_term = (self.log.term_at(prev)
                             if prev > self.snapshot_index else 0)
                entries = self.log.slice_from(nxt)
                wire = [(e.index, e.term, e.etype, e.payload)
                        for e in entries]
                term = self.term
                commit = self.commit_index
        try:
            if snap is not None:
                pterm = self.transport.call(peer, "rpc_install_snapshot",
                                            term, self.id, snap_index,
                                            snap_term, snap)
                with self._lock:
                    if pterm > self.term:
                        self._step_down_locked(pterm)
                        return
                    self._next[peer] = snap_index + 1
                    self._match[peer] = snap_index
                return
            pterm, ok, match = self.transport.call(
                peer, "rpc_append_entries", term, self.id, nxt - 1,
                prev_term, wire, commit)
        except ConnectionError:
            return
        with self._lock:
            if pterm > self.term:
                self._step_down_locked(pterm)
                return
            if self.role != ROLE_LEADER:
                return
            if ok:
                self._match[peer] = match
                self._next[peer] = match + 1
            else:
                self._next[peer] = max(1, min(nxt - 1, match + 1))

    def _advance_commit_locked(self) -> None:
        peers = self.cfg.peers or [self.id]
        matches = sorted((self._match.get(p, 0) for p in peers),
                        reverse=True)
        majority = matches[len(peers) // 2]
        # only commit entries from the CURRENT term by counting
        # (raft §5.4.2); prior-term entries commit transitively
        if majority > self.commit_index and \
                self.log.term_at(majority) == self.term:
            self.commit_index = majority
            # commit_index persistence is an optimization (bounds replay
            # on restart), not a safety requirement — batch it off the
            # hot path; stop()/compaction write the exact value
            if self.commit_index - self._meta_saved_commit >= 64:
                self._save_meta_locked()
            self._cv.notify_all()

    def _apply_committed_locked(self) -> None:
        while self.last_applied < self.commit_index:
            e = self.log.get(self.last_applied + 1)
            if e is None:
                break
            if e.etype == CONFIG:
                self._adopt_config_locked(list(e.payload))
            else:
                self.fsm.apply(e.index, e.etype, e.payload)
            self.last_applied = e.index
        self._cv.notify_all()
        if (self.log.last_index() - self.log.offset
                > self.cfg.snapshot_threshold):
            self._compact_locked()

    def _adopt_config_locked(self, peers: List[str]) -> None:
        """Adopt a committed membership change. Additions start
        replication from the leader's snapshot/backlog; removals stop
        counting toward quorum immediately (a removed self keeps
        applying until stopped — it simply never wins elections under
        the stickiness guard)."""
        old = set(self.cfg.peers)
        self.cfg.peers = list(peers)
        self._save_meta_locked()
        if self.role == ROLE_LEADER:
            if self.id not in peers:
                # a leader that committed its own removal steps down
                # (raft §6) — staying leader would let the stickiness
                # guard pin the cluster to a non-member forever
                self.role = ROLE_FOLLOWER
                self._reset_election_deadline_locked()
                self._role_events.append("follower")
                return
            for p in peers:
                if p not in old and p != self.id:
                    self._next[p] = self.log.last_index() + 1
                    self._match[p] = 0
            for p in old - set(peers):
                self._next.pop(p, None)
                self._match.pop(p, None)

    def add_learner(self, peer: str) -> None:
        """Start replicating to a NON-VOTING peer (it never counts
        toward quorum — _advance_commit iterates cfg.peers only)."""
        with self._lock:
            if peer not in self._staging and peer not in self.cfg.peers:
                self._staging.append(peer)
                self._next[peer] = self.log.last_index() + 1
                self._match[peer] = 0

    def learner_caught_up(self, peer: str) -> bool:
        with self._lock:
            # require real replicated progress: the peer must have acked
            # appends up to the current commit AND near the log head —
            # a freshly restored commit_index of 0 must not vacuously
            # pass a peer that holds nothing
            match = self._match.get(peer, 0)
            target = max(self.commit_index, self.log.last_index() - 1)
            return target > 0 and match >= target

    def remove_learner(self, peer: str) -> None:
        with self._lock:
            if peer in self._staging:
                self._staging.remove(peer)
            if peer not in self.cfg.peers:
                self._next.pop(peer, None)
                self._match.pop(peer, None)

    def propose_config(self, peers: List[str],
                       timeout: float = 10.0) -> int:
        """Propose a new peer set. One-at-a-time changes only (so old
        and new quorums always overlap, raft §6): the set may differ
        from the current config by a single server, and a previous
        membership change must be COMMITTED before the next — both
        checked under the same lock as the append, so concurrent
        callers cannot interleave conflicting configs into the log."""
        with self._lock:
            if self._closed:
                raise NotLeaderError(None)
            if self.role != ROLE_LEADER:
                raise NotLeaderError(self.leader_id)
            for e in self.log.slice_from(self.commit_index + 1):
                if e.etype == CONFIG:
                    raise ValueError(
                        "a membership change is already in flight")
            cur = set(self.cfg.peers)
            if len(cur.symmetric_difference(peers)) > 1:
                raise ValueError(
                    "membership changes must add or remove one server")
            index = self._append_locked(CONFIG, list(peers))
            term = self.term
        return self._wait_applied(index, term, timeout)

    # --------------------------------------------------------- snapshots
    def _compact_locked(self) -> None:
        data = self.fsm.snapshot()
        self.snapshot_term = self.log.term_at(self.last_applied)
        self.snapshot_index = self.last_applied
        if self._snap_path:
            tmp = self._snap_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, self._snap_path)
        self.log.compact_to(self.snapshot_index)
        self._save_meta_locked()

    def _read_snapshot(self) -> bytes:
        if self._snap_path and os.path.exists(self._snap_path):
            with open(self._snap_path, "rb") as f:
                return f.read()
        return self.fsm.snapshot()

    # ------------------------------------------------------ RPC handlers
    def rpc_request_vote(self, term: int, candidate: str,
                         last_log_index: int, last_log_term: int):
        with self._lock:
            if term < self.term:
                return self.term, False
            # leader stickiness (raft §6 disruptive-server guard, the
            # reference's CheckQuorum/pre-vote analog): while appends
            # from a live leader are arriving, refuse votes — a removed
            # server with a stale config cannot depose the leader
            lo, _hi = self.cfg.election_timeout_s
            if (self.role == ROLE_FOLLOWER
                    and time.monotonic() - self._last_leader_contact < lo
                    and candidate != self.voted_for):
                return self.term, False
            if term > self.term:
                self._step_down_locked(term)
            my_last = self.log.last_index()
            my_term = (self.log.term_at(my_last)
                       if my_last > self.snapshot_index
                       else self.snapshot_term)
            up_to_date = (last_log_term > my_term
                          or (last_log_term == my_term
                              and last_log_index >= my_last))
            if (self.voted_for in (None, candidate)) and up_to_date:
                self.voted_for = candidate
                self._save_meta_locked()
                self._reset_election_deadline_locked()
                return self.term, True
            return self.term, False

    def rpc_append_entries(self, term: int, leader: str, prev_index: int,
                           prev_term: int, entries, leader_commit: int):
        events = False
        with self._lock:
            if term < self.term:
                return self.term, False, 0
            if term > self.term or self.role != ROLE_FOLLOWER:
                was_leader = self.role == ROLE_LEADER
                self.term = term
                self.role = ROLE_FOLLOWER
                self.voted_for = None
                self._save_meta_locked()
                if was_leader:
                    self._role_events.append("follower")
                    events = True
            self.leader_id = leader
            self._last_leader_contact = time.monotonic()
            self._reset_election_deadline_locked()
            # consistency check
            if prev_index > self.snapshot_index:
                if (prev_index > self.log.last_index()
                        or self.log.term_at(prev_index) != prev_term):
                    return self.term, False, min(self.log.last_index(),
                                                 prev_index - 1)
            new = []
            for (i, t, y, p) in entries:
                existing_term = self.log.term_at(i)
                if i <= self.log.last_index():
                    if existing_term != t:
                        self.log.truncate_from(i)
                        new.append(LogEntry(i, t, y, p))
                else:
                    new.append(LogEntry(i, t, y, p))
            if new:
                self.log.append(new)
            match = prev_index + len(entries)
            if leader_commit > self.commit_index:
                self.commit_index = min(leader_commit,
                                        self.log.last_index())
                self._save_meta_locked()
            self._apply_committed_locked()
            out = self.term, True, match
        if events:
            self._fire_role_events()
        return out

    def rpc_install_snapshot(self, term: int, leader: str,
                             snap_index: int, snap_term: int, data: bytes):
        with self._lock:
            if term < self.term:
                return self.term
            self.term = term
            self.role = ROLE_FOLLOWER
            self.leader_id = leader
            self._last_leader_contact = time.monotonic()
            self._reset_election_deadline_locked()
            if snap_index <= self.last_applied:
                return self.term
            self.fsm.restore(data)
            self.snapshot_index = snap_index
            self.snapshot_term = snap_term
            self.last_applied = snap_index
            self.commit_index = max(self.commit_index, snap_index)
            self.log.compact_to(snap_index)
            if self._snap_path:
                tmp = self._snap_path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data if isinstance(data, bytes)
                            else bytes(data))
                os.replace(tmp, self._snap_path)
            self._save_meta_locked()
            return self.term
