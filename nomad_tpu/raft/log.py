"""Durable typed entry log (reference: hashicorp/raft LogStore backed by
raft-boltdb in nomad/server.go:1293; entry shape raft.Log).

Entries are JSON lines `{"i": index, "t": term, "y": type, "p": payload}`
appended to a single file and truncated from the front at snapshot time
(FileSnapshotStore analog) or from the back on follower conflict.
`data_dir=None` keeps the log purely in memory (tests, throwaway
clusters) — same interface, no files.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, List, Optional


@dataclass
class LogEntry:
    index: int
    term: int
    etype: str
    payload: Any


class RaftLog:
    def __init__(self, data_dir: Optional[str] = None,
                 fsync: bool = False):
        self._lock = threading.Lock()
        self.entries: List[LogEntry] = []
        self.offset = 0               # index of entries[0] - 1
        self._dir = data_dir
        self._fsync = fsync
        self._fh = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._path = os.path.join(data_dir, "raft.log")
            self._load()
            self._fh = open(self._path, "a", encoding="utf-8")

    # ------------------------------------------------------------ reads
    def last_index(self) -> int:
        with self._lock:
            return self.offset + len(self.entries)

    def term_at(self, index: int) -> int:
        with self._lock:
            if index <= self.offset or index > self.offset + len(self.entries):
                return 0
            return self.entries[index - self.offset - 1].term

    def get(self, index: int) -> Optional[LogEntry]:
        with self._lock:
            i = index - self.offset - 1
            if 0 <= i < len(self.entries):
                return self.entries[i]
            return None

    def slice_from(self, index: int, limit: int = 512) -> List[LogEntry]:
        with self._lock:
            i = max(index - self.offset - 1, 0)
            return self.entries[i:i + limit]

    # ----------------------------------------------------------- writes
    def append(self, entries: List[LogEntry]) -> None:
        with self._lock:
            self.entries.extend(entries)
            if self._fh:
                for e in entries:
                    self._fh.write(json.dumps(
                        {"i": e.index, "t": e.term, "y": e.etype,
                         "p": e.payload}, separators=(",", ":")) + "\n")
                self._fh.flush()
                if self._fsync:
                    os.fsync(self._fh.fileno())

    def truncate_from(self, index: int) -> None:
        """Drop index and everything after it (follower conflict)."""
        with self._lock:
            keep = max(index - self.offset - 1, 0)
            if keep >= len(self.entries):
                return
            del self.entries[keep:]
            self._rewrite_locked()

    def compact_to(self, index: int) -> None:
        """Drop everything up to and including `index` (it is captured in
        a snapshot)."""
        with self._lock:
            drop = index - self.offset
            if drop <= 0:
                return
            del self.entries[:drop]
            self.offset = index
            self._rewrite_locked()

    # ------------------------------------------------------------- disk
    def _rewrite_locked(self) -> None:
        # caller holds self._lock (truncate_from / compact_to)
        if not self._dir:
            return
        if self._fh:
            self._fh.close()
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps({"__offset__": self.offset}) + "\n")
            for e in self.entries:
                f.write(json.dumps({"i": e.index, "t": e.term,
                                    "y": e.etype, "p": e.payload},
                                   separators=(",", ":")) + "\n")
        os.replace(tmp, self._path)
        self._fh = open(self._path, "a", encoding="utf-8")

    def _load(self) -> None:
        if not os.path.exists(self._path):
            return
        # ctor-time only, but the lock is uncontended there and makes
        # the write discipline uniform
        with self._lock, open(self._path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break              # torn tail write: stop at the tear
                if "__offset__" in rec:
                    self.offset = rec["__offset__"]
                    self.entries.clear()
                    continue
                self.entries.append(LogEntry(rec["i"], rec["t"], rec["y"],
                                             rec["p"]))

    def close(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None
