"""Replicated log + consensus for the control plane.

The reference rides hashicorp/raft (nomad/server.go:1157 setupRaft) with
an FSM in nomad/fsm.go, BoltDB log storage, and FileSnapshotStore. This
package rebuilds that contract: a durable typed entry log (log.py), the
state-store FSM with snapshot/restore (fsm.py), and a raft node with
leader election, log replication, commit tracking and snapshot install
over pluggable transports (node.py — in-process for tests, TCP via the
rpc package).
"""
from .fsm import StateFSM
from .log import LogEntry, RaftLog
from .node import (InProcTransport, NotLeaderError, RaftConfig, RaftNode,
                   ROLE_CANDIDATE, ROLE_FOLLOWER, ROLE_LEADER)

__all__ = ["StateFSM", "LogEntry", "RaftLog", "InProcTransport",
           "NotLeaderError", "RaftConfig", "RaftNode", "ROLE_CANDIDATE",
           "ROLE_FOLLOWER", "ROLE_LEADER"]
