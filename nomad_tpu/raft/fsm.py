"""The replicated state machine over StateStore.

Reference: nomad/fsm.go — Apply dispatches typed log entries to state
store writes (fsm.go:180 switch), Snapshot persists every table
(fsm.go:1189), Restore rebuilds the store (fsm.go:1203). Entries here
carry plain-JSON payloads (utils/codec) so the same bytes serve the
durable log, snapshots, and the wire.

Determinism: every apply writes the store purely from (index, payload,
current store state) — timestamps are stamped by the proposer and travel
in the payload, so leader and followers converge bit-for-bit.
"""
from __future__ import annotations

import json
from typing import Any, Dict

from ..state.store import JobSummary, SchedulerConfiguration, StateStore
from ..structs import (Allocation, DeploymentStatusUpdate,
                       DesiredTransition, Deployment, Evaluation, Job, Node,
                       PlanResult)
from ..utils.codec import from_wire, to_wire

# entry type -> (payload struct fields needing decode)
NOOP = "noop"


class StateFSM:
    """Applies committed log entries to a StateStore. Broker enqueue is
    NOT done here: the leader's write paths enqueue after propose()
    returns (reference: fsm.go:680 handleUpsertedEval is leader-gated
    for the same reason — follower FSMs only write state)."""

    def __init__(self, store: StateStore):
        self.store = store

    # ------------------------------------------------------------ apply
    def apply(self, index: int, etype: str, p: Any) -> None:
        if etype == NOOP:
            return
        handler = getattr(self, "_ap_" + etype, None)
        if handler is None:
            raise ValueError(f"unknown raft entry type {etype!r}")
        handler(index, p)

    def _ap_node_upsert(self, index, p):
        self.store.upsert_node(index, from_wire(Node, p["node"]))

    def _ap_node_status(self, index, p):
        # a committed entry may target a node a racing reap already
        # deleted; the no-op is deterministic (same state, same order on
        # every replica) — raising would poison the log instead
        if self.store.node_by_id(p["node_id"]) is None:
            return
        self.store.update_node_status(index, p["node_id"], p["status"])

    def _ap_node_eligibility(self, index, p):
        if self.store.node_by_id(p["node_id"]) is None:
            return
        self.store.update_node_eligibility(index, p["node_id"],
                                           p["eligibility"])

    def _ap_node_drain(self, index, p):
        from ..structs import DrainStrategy
        if self.store.node_by_id(p["node_id"]) is None:
            return
        ds = from_wire(DrainStrategy, p["drain_strategy"]) \
            if p.get("drain_strategy") is not None else None
        self.store.update_node_drain(index, p["node_id"], ds,
                                     p.get("mark_eligible", False))

    def _ap_nodes_reap(self, index, p):
        for nid in p["node_ids"]:
            self.store.delete_node(index, nid)

    def _ap_job_upsert(self, index, p):
        self.store.upsert_job(index, from_wire(Job, p["job"]))

    def _ap_job_delete(self, index, p):
        self.store.delete_job(index, p["namespace"], p["job_id"])

    def _ap_jobs_reap(self, index, p):
        for namespace, job_id in p["keys"]:
            self.store.delete_job(index, namespace, job_id)

    def _ap_evals_upsert(self, index, p):
        self.store.upsert_evals(
            index, [from_wire(Evaluation, e) for e in p["evals"]])

    def _ap_evals_reap(self, index, p):
        self.store.delete_eval(index, p["eval_ids"], p["alloc_ids"])

    def _ap_allocs_client(self, index, p):
        self.store.update_allocs_from_client(
            index, [from_wire(Allocation, a) for a in p["updates"]])

    def _ap_alloc_transition(self, index, p):
        self.store.update_alloc_desired_transition(
            index, p["alloc_ids"],
            from_wire(DesiredTransition, p["transition"]))

    def _ap_plan_result(self, index, p):
        result = from_wire(PlanResult, p["result"])
        job = from_wire(Job, p["job"]) if p.get("job") is not None else None
        self.store.upsert_plan_results(index, result, job)

    def _ap_plan_results_batch(self, index, p):
        # group commit (ISSUE 17): K plan results in one log entry, in
        # submission order, all under the shared commit index — the same
        # store state K consecutive plan_result entries would produce
        for item in p["items"]:
            result = from_wire(PlanResult, item["result"])
            job = from_wire(Job, item["job"]) \
                if item.get("job") is not None else None
            self.store.upsert_plan_results(index, result, job)

    def _ap_job_stability(self, index, p):
        self.store.update_job_stability(index, p["namespace"],
                                        p["job_id"], p["version"],
                                        p["stable"])

    def _ap_deployment_status(self, index, p):
        self.store.upsert_deployment_updates(
            index,
            [from_wire(DeploymentStatusUpdate, u) for u in p["updates"]])
        if p.get("mark_stable") is not None:
            namespace, job_id, version = p["mark_stable"]
            self.store.update_job_stability(index, namespace, job_id,
                                            version, True)

    def _ap_deployment_promote(self, index, p):
        if self.store.deployment_by_id(p["dep_id"]) is None:
            return
        self.store.update_deployment_promotion(index, p["dep_id"],
                                               p.get("groups"))

    def _ap_deployments_reap(self, index, p):
        self.store.delete_deployment(index, p["dep_ids"])

    def _ap_periodic_launch(self, index, p):
        self.store.upsert_periodic_launch(index, p["namespace"],
                                          p["job_id"], p["launch"])

    def _ap_secret_upsert(self, index, p):
        self.store.upsert_secret(index, p["namespace"], p["path"],
                                 p["data"])

    def _ap_secret_delete(self, index, p):
        self.store.delete_secret(index, p["namespace"], p["path"])

    def _ap_acl_policy_upsert(self, index, p):
        from ..acl import ACLPolicy
        self.store.upsert_acl_policy(index,
                                     from_wire(ACLPolicy, p["policy"]))

    def _ap_acl_policy_delete(self, index, p):
        self.store.delete_acl_policy(index, p["name"])

    def _ap_acl_token_upsert(self, index, p):
        from ..acl import ACLToken
        self.store.upsert_acl_token(index,
                                    from_wire(ACLToken, p["token"]))
        if p.get("bootstrap"):
            self.store.set_acl_bootstrapped(index)

    def _ap_acl_token_delete(self, index, p):
        self.store.delete_acl_token(index, p["accessor_id"])

    def _ap_csi_volume_upsert(self, index, p):
        from ..structs import CSIVolume
        self.store.upsert_csi_volume(index,
                                     from_wire(CSIVolume, p["volume"]))

    def _ap_csi_volume_delete(self, index, p):
        try:
            self.store.delete_csi_volume(index, p["namespace"],
                                         p["volume_id"])
        except ValueError:
            pass    # in-use: deterministic no-op on every replica

    def _ap_csi_volume_claim(self, index, p):
        try:
            self.store.claim_csi_volume(
                index, p["namespace"], p["volume_id"], p["mode"],
                p["alloc_id"], p["node_id"])
        except (KeyError, ValueError):
            pass    # validated by the proposer; tolerate races

    def _ap_csi_claims_release(self, index, p):
        self.store.release_csi_claims(index, p["alloc_id"])

    def _ap_scheduler_config(self, index, p):
        cfg = SchedulerConfiguration()
        cfg.__dict__.update(p["config"])
        self.store.set_scheduler_config(index, cfg)

    # --------------------------------------------------------- snapshot
    _STRUCT_TABLES = {
        "nodes": Node, "jobs": Job, "evals": Evaluation,
        "allocs": Allocation, "deployments": Deployment,
    }
    _TUPLE_KEY_TABLES = ("jobs", "job_versions", "job_summaries",
                         "periodic_launches", "csi_volumes")

    def snapshot(self) -> bytes:
        """Serialize every replicated table (fsm.go:1189 Snapshot +
        nomad/state snapshot persisters)."""
        st = self.store
        with st._lock:
            out: Dict[str, Any] = {"latest_index": st.index,
                                   "table_indexes": dict(st._ix)}
            tables: Dict[str, list] = {}
            for name, cls in self._STRUCT_TABLES.items():
                tables[name] = [[self._key(name, k), to_wire(v)]
                                for k, v in st._t[name].items()]
            tables["job_versions"] = [
                [list(k), [to_wire(j) for j in v]]
                for k, v in st._t["job_versions"].items()]
            tables["job_summaries"] = [
                [list(k), to_wire(v)]
                for k, v in st._t["job_summaries"].items()]
            tables["periodic_launches"] = [
                [list(k), v] for k, v in st._t["periodic_launches"].items()]
            tables["csi_volumes"] = [
                [list(k), to_wire(v)]
                for k, v in st._t["csi_volumes"].items()]
            tables["acl_policies"] = [
                [k, to_wire(v)] for k, v in st._t["acl_policies"].items()]
            tables["acl_tokens"] = [
                [k, to_wire(v)] for k, v in st._t["acl_tokens"].items()]
            tables["cluster_meta"] = [
                [k, v] for k, v in st._t["cluster_meta"].items()]
            tables["services"] = [
                [k, to_wire(v)] for k, v in st._t["services"].items()]
            tables["secrets"] = [
                [list(k), v] for k, v in st._t["secrets"].items()]
            tables["scheduler_config"] = [
                [k, to_wire(v)] for k, v in st._t["scheduler_config"].items()]
            out["tables"] = tables
        return json.dumps(out, separators=(",", ":")).encode()

    def restore(self, data: bytes) -> None:
        """Rebuild the store from a snapshot (fsm.go:1203 Restore),
        including the derived secondary indexes."""
        snap = json.loads(data.decode())
        st = self.store
        with st._lock:
            for name in st._t:
                st._t[name].clear()
            t = snap["tables"]
            for name, cls in self._STRUCT_TABLES.items():
                for k, wire in t.get(name, ()):  # noqa: B007
                    st._t[name][self._unkey(name, k)] = from_wire(cls, wire)
            for k, versions in t.get("job_versions", ()):
                st._t["job_versions"][tuple(k)] = [
                    from_wire(Job, j) for j in versions]
            for k, wire in t.get("job_summaries", ()):
                s = JobSummary(wire.get("job_id", ""),
                               wire.get("namespace", "default"))
                s.__dict__.update(wire)
                st._t["job_summaries"][tuple(k)] = s
            for k, launch in t.get("periodic_launches", ()):
                st._t["periodic_launches"][tuple(k)] = launch
            from ..structs import CSIVolume
            for k, wire in t.get("csi_volumes", ()):
                st._t["csi_volumes"][tuple(k)] = from_wire(CSIVolume, wire)
            from ..acl import ACLPolicy, ACLToken
            for k, wire in t.get("acl_policies", ()):
                st._t["acl_policies"][k] = from_wire(ACLPolicy, wire)
            for k, wire in t.get("acl_tokens", ()):
                st._t["acl_tokens"][k] = from_wire(ACLToken, wire)
            for k, v in t.get("cluster_meta", ()):
                st._t["cluster_meta"][k] = v
            from ..structs.services import ServiceRegistration
            for k, wire in t.get("services", ()):
                st._t["services"][k] = from_wire(ServiceRegistration,
                                                 wire)
            for k, v in t.get("secrets", ()):
                st._t["secrets"][tuple(k)] = v
            for k, wire in t.get("scheduler_config", ()):
                cfg = SchedulerConfiguration()
                cfg.__dict__.update(wire)
                st._t["scheduler_config"][k] = cfg
            # rebuild derived indexes
            by_node: Dict[str, set] = {}
            by_job: Dict[tuple, set] = {}
            for a in st._t["allocs"].values():
                by_node.setdefault(a.node_id, set()).add(a.id)
                by_job.setdefault((a.namespace, a.job_id), set()).add(a.id)
            st._t["_allocs_by_node"] = by_node
            st._t["_allocs_by_job"] = by_job
            st._ix = dict(snap.get("table_indexes", {}))
            st.index = snap.get("latest_index", 0)
            st._watch.notify_all()

    @staticmethod
    def _key(table: str, k):
        return list(k) if table == "jobs" else k

    @staticmethod
    def _unkey(table: str, k):
        return tuple(k) if table == "jobs" else k
